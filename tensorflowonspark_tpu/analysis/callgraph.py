"""Module-graph + call-graph builder for the interprocedural tier.

graftcheck's original rules reason one function at a time; PR 6 made the
serving plane genuinely concurrent (device thread, host drain thread,
HTTP handler threads), and its invariants routinely cross a function
boundary: a taint enters a helper, a lock is taken two frames up, a
thread role is decided by ``Thread(target=...)`` in ``__init__`` and
consumed in a method five calls away.  This module builds the shared
substrate those analyses need, stdlib-``ast`` only:

- a **module graph**: every scanned package file keyed by its dotted
  module name, with its import table (``import x.y as z``, ``from .m
  import f as g``, relative imports resolved against the importing
  module's package);
- per-module **definition indexes**: module-level functions, classes
  with their method tables and (project-resolvable) base classes, and
  nested/closure functions chained to their lexical parent;
- a **call resolver**: given a ``Call`` node and the scope it occurs
  in, find the ``FunctionInfo`` it targets — ``self.method(...)``
  (through project-local base classes), bare names (closure chain →
  module level → ``from``-import), and ``mod.func(...)`` through the
  import table.

Resolution is deliberately *syntactic and best-effort*: a target built
dynamically (``getattr``, dicts of callables, functools.partial chains)
resolves to ``None`` and downstream analyses treat the call as opaque.
That is the right failure mode for a linter — missed edges cost recall,
never precision.
"""
from __future__ import annotations

import ast
import dataclasses

from .core import PACKAGE_DIR, _posix


def module_name(path):
    """Dotted module name for a scanned file path, or None for files
    outside the package (semantic rules only analyze the package)."""
    parts = _posix(path).split("/")
    if PACKAGE_DIR not in parts:
        return None
    parts = parts[parts.index(PACKAGE_DIR):]
    if not parts[-1].endswith(".py"):
        return None
    parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclasses.dataclass
class FunctionInfo:
    """One function or method definition."""
    name: str
    qualname: str              # e.g. serve.ContinuousBatcher._dispatch
    node: object               # ast.FunctionDef / ast.AsyncFunctionDef
    module: object             # ModuleInfo
    cls: object = None         # ClassInfo when a method
    parent: object = None      # lexical parent FunctionInfo (closures)
    # name -> FunctionInfo for functions defined directly in this body
    nested: dict = dataclasses.field(default_factory=dict)

    @property
    def params(self):
        a = self.node.args
        out = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        return out

    def __hash__(self):
        return id(self.node)

    def __eq__(self, other):
        return isinstance(other, FunctionInfo) and other.node is self.node


@dataclasses.dataclass
class ClassInfo:
    name: str
    node: object
    module: object
    methods: dict = dataclasses.field(default_factory=dict)
    base_names: list = dataclasses.field(default_factory=list)

    def method(self, name, graph=None, _seen=None):
        """Look `name` up on this class, then project-resolvable bases."""
        m = self.methods.get(name)
        if m is not None or graph is None:
            return m
        _seen = _seen or set()
        if id(self.node) in _seen:          # inheritance cycle guard
            return None
        _seen.add(id(self.node))
        for base in self.base_names:
            bci = graph.resolve_class(base, self.module)
            if bci is not None:
                m = bci.method(name, graph, _seen)
                if m is not None:
                    return m
        return None


@dataclasses.dataclass
class ModuleInfo:
    path: str
    modname: str
    ctx: object                # core.FileContext
    functions: dict = dataclasses.field(default_factory=dict)
    classes: dict = dataclasses.field(default_factory=dict)
    # import alias -> dotted module name ("np" -> "numpy")
    imports: dict = dataclasses.field(default_factory=dict)
    # local name -> (dotted module name, original name) for from-imports
    from_imports: dict = dataclasses.field(default_factory=dict)


def _resolve_relative(base_modname, level, module):
    """Absolute dotted name for a `from ...module import x` in
    `base_modname` (level dots).  A file module's package is its parent."""
    parts = base_modname.split(".")
    # level 1 = current package (drop the file component), each extra
    # level drops one more package
    parts = parts[:len(parts) - level]
    if module:
        parts += module.split(".")
    return ".".join(parts)


class CallGraph:
    """Project-wide definition index + call resolver.

    Build once per run (``CallGraph(project)``); rules share it through
    ``project.callgraph`` (see :func:`for_project`).
    """

    def __init__(self, project):
        self.modules = {}          # modname -> ModuleInfo
        self.by_path = {}          # posix path -> ModuleInfo
        # id(def node) -> FunctionInfo, for scope lookups by node
        self.info_by_node = {}
        for ctx in getattr(project, "files", []):
            if ctx.tree is None:
                continue
            modname = module_name(ctx.path)
            if modname is None:
                continue
            mi = ModuleInfo(path=ctx.path, modname=modname, ctx=ctx)
            self._index_module(mi)
            self.modules[modname] = mi
            self.by_path[_posix(ctx.path)] = mi

    # ---- indexing --------------------------------------------------------

    def _index_module(self, mi):
        for node in mi.ctx.tree.body:
            self._index_stmt(node, mi, cls=None, parent=None)
        for node in ast.walk(mi.ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mi.imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom):
                target = (node.module or "")
                if node.level:
                    target = _resolve_relative(mi.modname, node.level,
                                               node.module or "")
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    mi.from_imports[alias.asname or alias.name] = \
                        (target, alias.name)

    def _index_stmt(self, node, mi, cls, parent):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = ".".join(
                p for p in (mi.modname.rsplit(".", 1)[-1],
                            cls.name if cls else None,
                            (parent.name + ".<locals>") if parent else None,
                            node.name) if p)
            fi = FunctionInfo(name=node.name, qualname=qual, node=node,
                              module=mi, cls=cls, parent=parent)
            self.info_by_node[id(node)] = fi
            if parent is not None:
                parent.nested[node.name] = fi
            elif cls is not None:
                cls.methods[node.name] = fi
            else:
                mi.functions.setdefault(node.name, fi)
            for sub in node.body:
                self._index_stmt(sub, mi, cls=None, parent=fi)
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo(name=node.name, node=node, module=mi,
                           base_names=[_dotted(b) for b in node.bases])
            mi.classes.setdefault(node.name, ci)
            for sub in node.body:
                self._index_stmt(sub, mi, cls=ci, parent=None)
        elif isinstance(node, (ast.If, ast.Try, ast.With)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.stmt):
                    self._index_stmt(sub, mi, cls, parent)

    # ---- lookups ---------------------------------------------------------

    def function_info(self, def_node):
        return self.info_by_node.get(id(def_node))

    def resolve_class(self, dotted, mi):
        """ClassInfo for a (possibly dotted/imported) class name as seen
        from module `mi`."""
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        if not rest and head in mi.classes:
            return mi.classes[head]
        if head in mi.from_imports:
            target_mod, orig = mi.from_imports[head]
            tmi = self.modules.get(target_mod)
            if tmi is not None:
                if not rest:
                    return tmi.classes.get(orig)
            # `from . import serve` then serve.Class
            tmi = self.modules.get(f"{target_mod}.{orig}"
                                   if target_mod else orig)
            if tmi is not None and rest and "." not in rest:
                return tmi.classes.get(rest)
        if head in mi.imports and rest and "." not in rest:
            tmi = self.modules.get(mi.imports[head])
            if tmi is not None:
                return tmi.classes.get(rest)
        return None

    def resolve_call(self, func_expr, scope):
        """FunctionInfo targeted by calling `func_expr` from `scope`
        (a FunctionInfo, or a ModuleInfo for module-level code); None
        when the target is dynamic or outside the project."""
        mi = scope.module if isinstance(scope, FunctionInfo) else scope
        if isinstance(func_expr, ast.Name):
            name = func_expr.id
            # closure chain first (lexical scoping)
            fn = scope if isinstance(scope, FunctionInfo) else None
            while fn is not None:
                if name in fn.nested:
                    return fn.nested[name]
                fn = fn.parent
            if name in mi.functions:
                return mi.functions[name]
            if name in mi.from_imports:
                target_mod, orig = mi.from_imports[name]
                tmi = self.modules.get(target_mod)
                if tmi is not None:
                    return tmi.functions.get(orig)
            return None
        if isinstance(func_expr, ast.Attribute):
            base = func_expr.value
            # self.method(...) inside a class
            if (isinstance(base, ast.Name) and base.id == "self"
                    and isinstance(scope, FunctionInfo)
                    and scope.cls is not None):
                return scope.cls.method(func_expr.attr, self)
            # cls.method(...) via classname
            if isinstance(base, ast.Name):
                ci = self.resolve_class(base.id, mi)
                if ci is not None:
                    return ci.method(func_expr.attr, self)
                # imported_module.func(...)
                tm = None
                if base.id in mi.imports:
                    tm = self.modules.get(mi.imports[base.id])
                elif base.id in mi.from_imports:
                    target_mod, orig = mi.from_imports[base.id]
                    tm = self.modules.get(
                        f"{target_mod}.{orig}" if target_mod else orig)
                    if tm is None and target_mod:
                        # `from . import x` where x is a name IN target_mod
                        tm = None
                if tm is not None:
                    return tm.functions.get(func_expr.attr)
        return None


def _dotted(expr):
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def for_project(project):
    """Build (or reuse) the project's CallGraph.  Cached on the project
    object so every interprocedural rule shares one index per run."""
    cg = getattr(project, "_callgraph", None)
    if cg is None:
        cg = CallGraph(project)
        project._callgraph = cg
    return cg
