"""graftcheck — JAX/TPU-aware stdlib static analysis.

Rule framework + the semantic analyzers (tracer hazards, sharding lint,
Pallas tile checks, lock discipline, thread-role races, resource
lifecycles, jit-recompile lint, wire-protocol contracts) + the style
tier scripts/lint.py delegates to.  Run as ``python
scripts/graftcheck.py`` or ``python -m tensorflowonspark_tpu.analysis``;
see docs/source/analysis.rst.
"""
from .core import (Finding, Project, Rule, REGISTRY, analyze_source,  # noqa: F401
                   main, register, run_rules)

__all__ = ["Finding", "Project", "Rule", "REGISTRY", "analyze_source",
           "main", "register", "run_rules"]
