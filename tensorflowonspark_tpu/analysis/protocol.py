"""Declarative model of the fleet's wire protocol (graftcheck wireproto).

The serving plane speaks an *informal* protocol: HTTP routes are
``if path == ...`` chains in ``serve.py``/``fleet.py``, the rendezvous
and KV-transfer planes dispatch on ``msg["type"]`` / ``req["kind"]``
string compares, and contract fields (``priority``, ``trace``, ``seed``,
``Idempotency-Key``) must be re-written by hand into every carrier
payload — journal replay bodies, wire snapshots, job records.  Nothing
type-checks any of it.  ``analysis/wireproto.py`` extracts the protocol
from the AST; this module declares what the extractor cannot infer:

- the dataclasses the extraction produces (``Endpoint``,
  ``ClientCall``, ``MessageCase``) — also the shape of the
  ``--format protocol`` JSON dump;
- :data:`FIELD_SPECS` — the :class:`PropagatedFieldSpec` table (the
  PR 8 ``ResourceSpec`` pattern): one row per contract field naming
  every carrier function that must write it, checked by
  ``wire-dropped-field``;
- :data:`EXTERNAL_ENDPOINTS` / :data:`ACK_MESSAGES` — server surfaces
  with no in-repo client *by design* (Prometheus scrapes, operator
  curl, protocol ack frames), each with its rationale.  Everything
  else unmatched is a ``wire-dead-endpoint`` finding.

Like ``resources.py``, growing the protocol is a table edit, not an
analyzer change: a new endpoint that rides an existing idiom is
extracted automatically, a new contract field is one
:class:`PropagatedFieldSpec` row, and a new operator-only surface is
one allowlist entry with a rationale string.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class Endpoint:
    """One server route: a (method, path-pattern) a handler answers.

    ``path`` is the normalized pattern: literal segments kept, every
    dynamic piece (f-string interpolation, ``startswith`` tail)
    collapsed to ``*`` — ``/v1/models/*:generate``, ``/v1/trace/*``.
    ``kind`` records how the handler matched: ``exact``, ``prefix``
    (a bare ``startswith``), or ``verb`` (prefix + ``:verb`` suffix).
    """
    method: str
    path: str
    layer: str                 # module short name: "serve" / "fleet"
    handler: str               # qualname of the do_GET/do_POST
    line: int
    kind: str = "exact"
    statuses: tuple = ()       # literal codes; "*" = relayed/dynamic

    def as_dict(self):
        return {"method": self.method, "path": self.path,
                "layer": self.layer, "handler": self.handler,
                "line": self.line, "kind": self.kind,
                "statuses": sorted(self.statuses, key=str)}


@dataclasses.dataclass
class ClientCall:
    """One client emission site: a call that puts a request on the wire.

    ``path`` is normalized like :class:`Endpoint.path` (querystrings
    stripped); ``None`` means the path is dynamic (a relay forwarding
    ``self.path``) and the site is exempt from endpoint matching.
    ``statuses`` are the literal codes the surrounding function's
    status checks distinguish; ``retried`` marks sites re-driven by a
    retry loop (their status handling feeds ``wire-status-unhandled``).
    """
    method: str
    path: object               # str pattern or None (dynamic relay)
    layer: str
    caller: str                # qualname of the emitting function
    line: int
    headers: tuple = ()
    body_fields: tuple = ()
    statuses: tuple = ()
    retried: bool = False

    def as_dict(self):
        return {"method": self.method, "path": self.path,
                "layer": self.layer, "caller": self.caller,
                "line": self.line, "headers": sorted(self.headers),
                "body_fields": sorted(self.body_fields),
                "statuses_distinguished": sorted(self.statuses, key=str),
                "retried": self.retried}


@dataclasses.dataclass
class MessageCase:
    """One message-plane case: a ``{"type": X}`` / ``{"kind": X}``
    constant either dispatched on by a server loop (``side="handle"``)
    or put on the wire by a send (``side="emit"``)."""
    key: str                   # the dispatch key: "type" or "kind"
    value: str                 # the constant: "REG", "pull", ...
    side: str                  # "handle" | "emit"
    layer: str
    where: str                 # qualname
    line: int

    def as_dict(self):
        return {"key": self.key, "value": self.value, "side": self.side,
                "layer": self.layer, "where": self.where,
                "line": self.line}


# ---------------------------------------------------------------------------
# propagated contract fields


@dataclasses.dataclass(frozen=True)
class PropagatedFieldSpec:
    """One contract field and the carrier payloads it must survive.

    ``carriers`` are ``"module.function"`` patterns — the module's last
    dotted component plus the bare function/method name (class names
    are deliberately not part of the pattern, same suffix-matching
    spirit as ``ResourceSpec``).  ``wire-dropped-field`` resolves each
    pattern through the call graph and verifies the function (or a
    same-project callee, depth-bounded) writes the field into a
    mapping: a dict-literal key, a ``d["field"] = ...`` store, a
    ``d.setdefault("field", ...)``, or a ``dict(field=...)`` keyword.

    A carrier pattern that resolves to no scanned function is skipped,
    not flagged — specs survive refactors that delete a carrier, and
    fixture projects exercise single specs in isolation.
    """
    field: str
    carriers: tuple
    description: str


# The contract fields the fleet promises survive every hop (serving.rst
# "Multi-tenant scheduling" / "Request tracing" / "Crash recovery").
# Each carrier builds a payload that crosses a process boundary; a
# carrier that stops writing the field silently demotes every session
# on that path — exactly the bug class wire_snapshot shipped with
# (priority was dropped on the migration path until this table landed).
FIELD_SPECS = (
    PropagatedFieldSpec(
        field="priority",
        carriers=("fleet._replay_meta",        # journal re-drive body
                  "fleet._stream_generate",    # journaled stream body
                  "fleet._route_models",       # non-stream relay body
                  "kvtransfer.wire_snapshot",  # migration/park meta
                  "jobs.record_request"),      # bulk-job request body
        description="tenant priority class: a re-driven, migrated, "
                    "parked, or job-dispatched session must admit "
                    "under the class the first drive resolved",
    ),
    PropagatedFieldSpec(
        field="trace",
        carriers=("fleet._replay_meta",
                  "fleet._stream_generate",
                  "fleet._route_models",
                  "kvtransfer.wire_snapshot"),
        description="trace id: every hop (replay, migration, park) "
                    "must record spans under the request's one id so "
                    "GET /v1/trace/<id> stitches one timeline",
    ),
    PropagatedFieldSpec(
        field="seed",
        carriers=("fleet._seed_body",          # gateway seeds pre-journal
                  "fleet._replay_meta",
                  "kvtransfer.wire_snapshot",
                  "jobs.record_request"),
        description="sampling seed: byte-identical recovery rests on "
                    "noise being a pure function of (seed, ordinal) — "
                    "a carrier that drops the seed breaks replay parity",
    ),
    PropagatedFieldSpec(
        field="Idempotency-Key",
        carriers=("fleet._attempt_stream",     # drive + re-drive headers
                  "jobs._dispatch_gateway"),   # job record re-dispatch
        description="exactly-once key: a re-drive or re-dispatch whose "
                    "predecessor is still decoding must dedupe on the "
                    "replica instead of double-generating",
    ),
)


# ---------------------------------------------------------------------------
# surfaces with no in-repo client, by design


# (method, path-pattern) -> rationale.  These endpoints are driven from
# OUTSIDE the repo — Prometheus scrapers, operator curl, load-balancer
# checks — so "no client emission matches" is the expected state, not a
# dead route.  wire-dead-endpoint skips them; the protocol dump still
# lists them (with the rationale) so the docs-drift test covers them.
EXTERNAL_ENDPOINTS = {
    ("GET", "/metrics"):
        "Prometheus scrape target (text exposition); no in-repo client",
    ("GET", "/v1/metrics"):
        "alias of /metrics for path-prefixed scrape configs",
    ("GET", "/"):
        "human/browser landing alias of the metadata endpoint",
    ("GET", "/v1/trace/*"):
        "operator timeline lookup; the gateway stitches replicas "
        "itself via an internal probe, clients use curl",
    ("POST", "/v1/debug:profile"):
        "operator-triggered jax.profiler capture (the gateway proxies "
        "the same path to a replica, which keeps the pair matched)",
}


# Modules that speak a framed message plane, and the dict key their
# dispatch switches on.  Extraction is gated on this table so that
# unrelated `x["kind"]` compares elsewhere in the repo (snapshot
# layout tags, config dicts) never read as protocol dispatch.
MESSAGE_PLANES = {
    "reservation": "type",     # rendezvous RPCs: REG/QUERY/BEAT/...
    "kvtransfer": "kind",      # page-server frames: pull/header/block/...
}


# Message-plane constants that are *replies*, not requests: the
# request/response planes share one framed socket, so a reply frame is
# "emitted" by the server dispatcher yet dispatched on by no one —
# clients treat any non-exception reply as the ack and surface ERR
# payload text through exceptions rather than a type switch.
ACK_MESSAGES = {
    ("type", "OK"):
        "rendezvous ack frame; clients treat any reply as success",
    ("type", "ERR"):
        "rendezvous error reply; surfaced as raised text, not dispatched",
}
