"""Tracer-hazard analyzer: host round-trips and Python control flow on
traced values inside jit/pjit/shard_map-staged functions.

Under ``jax.jit`` the function body runs once with abstract tracers;
anything that needs a concrete value — ``float(x)``, ``x.item()``,
``np.asarray(x)``, ``if x > 0`` — either raises a
``ConcretizationTypeError`` at trace time or (worse, for side effects like
``print``) silently runs only at trace time.  pytest on CPU catches the
loud failures; this rule catches them before any run, and catches the
silent ones pytest cannot.

Detection is a per-function taint walk: the jitted function's array
parameters (minus ``static_argnums``/``static_argnames``) seed the taint
set; assignments, arithmetic, subscripts, and calls propagate it; the
static-under-trace attributes (``.shape``/``.dtype``/``.ndim``) launder it.
Jitted functions are found by decorator (``@jax.jit``,
``@partial(jax.jit, ...)``, ``@shard_map``-style) and by same-module
wrapping calls (``f2 = jax.jit(f)``, ``compat.shard_map(f, mesh=...)``).

Interprocedural tier: calls out of a staged function to a resolvable
project helper consult the helper's dataflow summary
(:mod:`.dataflow`), so ``float(x)`` buried one or two helper frames
down still reports — at the staged call site, naming the helper line.
"""
from __future__ import annotations

import ast

from . import callgraph
from .core import Finding, Rule, register
from .dataflow import EMPTY, Hazard, OriginWalker, SummaryEngine, call_name

# Attributes that are static (Python values) even on a tracer.
_STATIC_ATTRS = {"shape", "dtype", "ndim", "weak_type", "sharding", "aval"}
# Builtins whose result is static even with a traced argument.
_SHAPE_FNS = {"len", "isinstance", "type", "id", "repr", "str", "format"}
_CAST_FNS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist", "numpy", "to_py"}
_NUMPY_ROOTS = {"np", "numpy", "onp"}
_NUMPY_FORCERS = {"asarray", "array", "asanyarray", "ascontiguousarray"}
_STAGING_NAMES = {"jit", "pjit", "shard_map"}


def _call_name(fn):
    """Dotted name of a call target, e.g. 'jax.jit' or 'jit'; None if the
    target is not a plain name/attribute chain."""
    parts = []
    while isinstance(fn, ast.Attribute):
        parts.append(fn.attr)
        fn = fn.value
    if isinstance(fn, ast.Name):
        parts.append(fn.id)
        return ".".join(reversed(parts))
    return None


def _is_staging(name):
    return name is not None and name.split(".")[-1] in _STAGING_NAMES


def _static_filter(call_kwargs):
    """(static_argnums, static_argnames) pulled from jit(...) keywords with
    literal values; non-literal values are ignored (best effort)."""
    nums, names = set(), set()
    for kw in call_kwargs:
        if kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return nums, names


def _staged_functions(tree):
    """Yield (FunctionDef, static_argnums, static_argnames, how) for every
    function staged by jit/pjit/shard_map in this module."""
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    name = _call_name(dec.func)
                    if _is_staging(name):  # @shard_map(mesh=...)-style factory
                        nums, names = _static_filter(dec.keywords)
                        yield node, nums, names, name
                    elif name is not None and name.split(".")[-1] == "partial":
                        if dec.args and _is_staging(_call_name(dec.args[0])):
                            nums, names = _static_filter(dec.keywords)
                            yield node, nums, names, _call_name(dec.args[0])
                else:
                    name = _call_name(dec)
                    if _is_staging(name):
                        yield node, set(), set(), name
        elif isinstance(node, ast.Call):
            name = _call_name(node.func)
            if _is_staging(name) and node.args and isinstance(node.args[0], ast.Name):
                target = defs.get(node.args[0].id)
                if target is not None:
                    nums, names = _static_filter(node.keywords)
                    yield target, nums, names, name


class _TaintWalker(ast.NodeVisitor):
    def __init__(self, rule, ctx, fn, tainted, staged_as, engine=None):
        self.rule = rule
        self.ctx = ctx
        self.fn = fn
        self.tainted = tainted
        self.staged_as = staged_as
        self.engine = engine        # dataflow.SummaryEngine (interproc) or None
        self.findings = []

    # -- taint query -------------------------------------------------------
    def is_tainted(self, node):
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is an identity (presence) check:
            # static under trace even when x is a tracer — the repo's
            # PRESENCE-static optional-argument idiom depends on it.
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return (self.is_tainted(node.body) or self.is_tainted(node.orelse)
                    or self.is_tainted(node.test))
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            name = _call_name(node.func)
            base = name.split(".")[-1] if name else None
            if base in _SHAPE_FNS:
                return False
            if isinstance(node.func, ast.Attribute) and self.is_tainted(node.func.value):
                return True
            return any(self.is_tainted(a) for a in node.args) or any(
                self.is_tainted(k.value) for k in node.keywords)
        return False

    # -- taint propagation -------------------------------------------------
    def _bind(self, target, tainted):
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)

    def visit_Assign(self, node):
        self.visit(node.value)
        t = self.is_tainted(node.value)
        for tgt in node.targets:
            self._bind(tgt, t)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        if self.is_tainted(node.value):
            self._bind(node.target, True)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
            self._bind(node.target, self.is_tainted(node.value))

    # -- hazards -----------------------------------------------------------
    def _flag(self, node, rule_name, msg):
        self.findings.append(Finding(self.ctx.path, node.lineno, rule_name, msg))

    def visit_Call(self, node):
        name = _call_name(node.func)
        base = name.split(".")[-1] if name else None
        arg_tainted = any(self.is_tainted(a) for a in node.args)

        if base in _CAST_FNS and name == base and arg_tainted:
            self._flag(node, "tracer-host-cast",
                       f"{base}() on a traced value inside {self.staged_as}"
                       f"-staged '{self.fn.name}' forces a host round-trip "
                       "(ConcretizationTypeError at trace time); keep it as "
                       "an array or mark the argument static")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _HOST_METHODS
              and self.is_tainted(node.func.value)):
            self._flag(node, "tracer-host-cast",
                       f".{node.func.attr}() on a traced value inside "
                       f"{self.staged_as}-staged '{self.fn.name}' forces a "
                       "host round-trip; move it outside the staged function")
        elif (name is not None and "." in name
              and name.split(".")[0] in _NUMPY_ROOTS
              and base in _NUMPY_FORCERS and arg_tainted):
            self._flag(node, "tracer-host-cast",
                       f"{name}() concretizes a traced value inside "
                       f"{self.staged_as}-staged '{self.fn.name}'; use jnp")
        elif name == "print" and self.staged_as is not None:
            self._flag(node, "tracer-side-effect",
                       f"print() inside {self.staged_as}-staged "
                       f"'{self.fn.name}' runs only at trace time; use "
                       "jax.debug.print()")
        elif self.engine is not None:
            self._check_callee(node)
        self.generic_visit(node)

    def _check_callee(self, node):
        """Interprocedural step: when the callee is a project-local helper,
        instantiate its hazard summary against the taint of the actual
        arguments, so a host cast one (or two) helper frames down still
        reports — at THIS call site, naming the helper line."""
        hazards = _callee_hazards(self.engine, node, self.fn,
                                  lambda e: self.is_tainted(e))
        for fi, hz in hazards:
            self._flag(node, hz.rule,
                       f"{hz.message} in helper '{fi.name}' (line {hz.line})"
                       f" reached with a traced value from {self.staged_as}"
                       f"-staged '{self.fn.name}'")

    def visit_If(self, node):
        if self.is_tainted(node.test):
            self._flag(node, "tracer-python-branch",
                       f"Python `if` on a traced value inside {self.staged_as}"
                       f"-staged '{self.fn.name}'; use jnp.where or "
                       "jax.lax.cond")
        self.generic_visit(node)

    def visit_While(self, node):
        if self.is_tainted(node.test):
            self._flag(node, "tracer-python-branch",
                       f"Python `while` on a traced value inside "
                       f"{self.staged_as}-staged '{self.fn.name}'; use "
                       "jax.lax.while_loop")
        self.generic_visit(node)

    def visit_Assert(self, node):
        if self.is_tainted(node.test):
            self._flag(node, "tracer-python-branch",
                       f"`assert` on a traced value inside {self.staged_as}"
                       f"-staged '{self.fn.name}'; use "
                       "jax.debug.check or checkify")
        self.generic_visit(node)

    # Don't descend into nested function definitions with the same taint
    # frame's *parameters* — but closures do see outer locals, so keep the
    # shared taint set and just walk the body.
    def visit_FunctionDef(self, node):
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


class _TracerOriginWalker(OriginWalker):
    """Origin-set mirror of _TaintWalker used to SUMMARIZE helper
    functions: same hazard classes, but each records which parameters it
    fires for, so call sites instantiate them against actual-argument
    taint.  Messages here are fragments; the reporting walker wraps them
    with the helper/staged-function context."""

    def on_call(self, node):
        name = call_name(node.func)
        base = name.split(".")[-1] if name else None
        arg_origins = EMPTY
        for a in node.args:
            arg_origins |= self.origins(a)
        if base in _CAST_FNS and name == base and arg_origins:
            self.hazards.append(Hazard(
                arg_origins, "tracer-host-cast",
                f"{base}() forces a host round-trip", node.lineno))
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _HOST_METHODS
              and self.origins(node.func.value)):
            self.hazards.append(Hazard(
                self.origins(node.func.value), "tracer-host-cast",
                f".{node.func.attr}() forces a host round-trip",
                node.lineno))
        elif (name is not None and "." in name
              and name.split(".")[0] in _NUMPY_ROOTS
              and base in _NUMPY_FORCERS and arg_origins):
            self.hazards.append(Hazard(
                arg_origins, "tracer-host-cast",
                f"{name}() concretizes the value", node.lineno))
        elif name == "print":
            self.hazards.append(Hazard(
                EMPTY, "tracer-side-effect",
                "print() runs only at trace time", node.lineno))
        else:
            self.instantiate_callee_hazards(node)

    def _branch(self, node, what, fix):
        o = self.origins(node.test)
        if o:
            self.hazards.append(Hazard(
                o, "tracer-python-branch",
                f"Python `{what}` on the value ({fix})", node.lineno))

    def visit_If(self, node):
        self._branch(node, "if", "use jnp.where or jax.lax.cond")
        self.generic_visit(node)

    def visit_While(self, node):
        self._branch(node, "while", "use jax.lax.while_loop")
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._branch(node, "assert", "use jax.debug.check or checkify")
        self.generic_visit(node)


def _make_engine(ctx):
    """Project-shared SummaryEngine for the tracer walkers, or None when
    the scan has no resolvable package files (single-snippet tests still
    resolve same-module helpers through their own FileContext)."""
    project = ctx.project
    if project is None or not getattr(project, "files", None):
        return None
    engine = getattr(project, "_tracer_engine", None)
    if engine is None:
        cg = callgraph.for_project(project)
        if not cg.modules:
            return None
        engine = SummaryEngine(
            cg, lambda e, fi, depth: _TracerOriginWalker(e, fi, depth))
        engine._staged_ids = None
        project._tracer_engine = engine
    return engine


def _staged_node_ids(engine):
    if engine._staged_ids is None:
        ids = set()
        for mi in engine.callgraph.modules.values():
            for fn, _n, _s, _how in _staged_functions(mi.ctx.tree):
                ids.add(id(fn))
        engine._staged_ids = ids
    return engine._staged_ids


def _callee_hazards(engine, node, caller_fn, tainted_pred):
    """(FunctionInfo, Hazard) pairs live at this call site: the callee's
    summarized hazards whose origin parameters are bound to tainted
    actuals (plus unconditional ones).  Callees that are themselves
    staged are skipped — the tracer checks them directly at their own
    definition."""
    cg = engine.callgraph
    scope = cg.function_info(caller_fn)
    if scope is None:
        return []
    fi = cg.resolve_call(node.func, scope)
    if fi is None or id(fi.node) in _staged_node_ids(engine):
        return []
    summary = engine.summary(fi)
    if not summary.hazards:
        return []
    params = fi.params
    if params and params[0] == "self" and isinstance(node.func,
                                                     ast.Attribute):
        params = params[1:]
    binding = {}
    for i, a in enumerate(node.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(params):
            binding[params[i]] = tainted_pred(a)
    for kw in node.keywords:
        if kw.arg is not None:
            binding[kw.arg] = tainted_pred(kw.value)
    return [(fi, hz) for hz in summary.hazards
            if not hz.origins or any(binding.get(o) for o in hz.origins)]


class _TracerRuleBase(Rule):
    """Shared machinery; three registered names so suppressions and
    `--select` can address each hazard class separately."""

    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        engine = _make_engine(ctx)
        seen = set()
        for fn, static_nums, static_names, how in _staged_functions(ctx.tree):
            key = (fn.lineno, fn.name)
            if key in seen:
                continue
            seen.add(key)
            params = []
            a = fn.args
            params.extend(p.arg for p in a.posonlyargs + a.args)
            tainted = set()
            for i, p in enumerate(params):
                if i in static_nums or p in static_names:
                    continue
                tainted.add(p)
            tainted.update(p.arg for p in a.kwonlyargs
                           if p.arg not in static_names)
            tainted.discard("self")
            w = _TaintWalker(self, ctx, fn, tainted, how.split(".")[-1],
                             engine=engine)
            for stmt in fn.body:
                w.visit(stmt)
            for f in w.findings:
                if f.rule == self.name:
                    yield f


@register
class TracerHostCastRule(_TracerRuleBase):
    name = "tracer-host-cast"
    description = ("float()/int()/.item()/.tolist()/np.asarray on a traced "
                   "value inside a jit/pjit/shard_map function")


@register
class TracerPythonBranchRule(_TracerRuleBase):
    name = "tracer-python-branch"
    description = "Python if/while/assert on a traced value inside a staged function"


@register
class TracerSideEffectRule(_TracerRuleBase):
    name = "tracer-side-effect"
    description = "side-effecting call (print) inside a staged function"
