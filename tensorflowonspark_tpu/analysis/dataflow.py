"""Forward dataflow engine: origin-set taint with memoized per-function
summaries along call-graph edges.

The intra-function rules (tracer, hostsync) walk one body and ask "is
this expression derived from a tainted parameter?".  This module answers
the same question *across* a call: each function gets a **summary** —
which of its parameters flow into which hazards, and which parameters
its return value derives from — computed once and memoized, so a caller
can instantiate the summary against its own taint state at every call
site in O(1).

Design points:

- **Origin sets, not booleans.**  Taint is tracked as the set of
  parameter names an expression derives from.  A hazard inside a helper
  records its origin set; at the call site it fires only if one of the
  *actual* arguments bound to those origins is tainted in the caller.
  A hazard with an EMPTY origin set is unconditional (``print`` under
  trace, a blocking sync in a hot path) and fires at every call site.
- **Depth bound.**  Summaries chase calls ``max_depth`` levels deep
  (default 2 — "taint survives one level of helper calls" plus one for
  trivial forwarding wrappers).  At the bound, calls go opaque: result
  taint is the union of argument taints (conservative), no hazards.
- **Cycle safe.**  A function currently being summarized (direct or
  mutual recursion) is treated as opaque at the recursive edge; the
  completed summary is memoized, so cycles terminate with the same
  conservative default the depth bound uses.

The walker here is the superset of tracer.py's boolean walker (same
laundering rules: ``.shape``/``.dtype`` metadata, ``is None`` presence
checks, shape builtins); rule modules subclass :class:`OriginWalker`
to add their hazard hooks and plug it into a :class:`SummaryEngine`.
"""
from __future__ import annotations

import ast
import dataclasses

# Shared with tracer.py (kept here so dataflow has no rule imports; the
# rule modules re-use these same sets).
STATIC_ATTRS = {"shape", "dtype", "ndim", "weak_type", "sharding", "aval"}
SHAPE_FNS = {"len", "isinstance", "type", "id", "repr", "str", "format"}


def call_name(fn):
    """Dotted name of a call target ('jax.jit', 'jit'); None when the
    target is not a plain name/attribute chain."""
    parts = []
    while isinstance(fn, ast.Attribute):
        parts.append(fn.attr)
        fn = fn.value
    if isinstance(fn, ast.Name):
        parts.append(fn.id)
        return ".".join(reversed(parts))
    return None


EMPTY = frozenset()


@dataclasses.dataclass
class Hazard:
    """One potential finding inside a summarized function.  ``origins``
    names the parameters whose taint triggers it (empty = fires
    unconditionally); ``line`` is where it sits in the CALLEE (the
    caller reports at its own call-site line, mentioning this one)."""
    origins: frozenset
    rule: str
    message: str
    line: int


@dataclasses.dataclass
class Summary:
    hazards: list
    ret_origins: frozenset

    @classmethod
    def opaque(cls, params=()):
        # conservative default: result derives from every parameter,
        # nothing observable inside
        return cls(hazards=[], ret_origins=frozenset(params))


class OriginWalker(ast.NodeVisitor):
    """Taint propagation with origin sets.

    ``env`` maps local names to frozensets of origin labels (the
    summarized function's parameter names).  Subclasses override
    ``on_call(node, origins_of_args)`` and the statement hooks to record
    hazards into ``self.hazards``.
    """

    def __init__(self, engine=None, scope=None, depth=0):
        self.env = {}
        self.engine = engine        # SummaryEngine or None
        self.scope = scope          # FunctionInfo for call resolution
        self.depth = depth
        self.hazards = []
        self.ret_origins = EMPTY

    # ---- origin query ----------------------------------------------------

    def origins(self, node):
        if isinstance(node, ast.Name):
            return self.env.get(node.id, EMPTY)
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return EMPTY
            return self.origins(node.value)
        if isinstance(node, ast.Subscript):
            return self.origins(node.value)
        if isinstance(node, ast.BinOp):
            return self.origins(node.left) | self.origins(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.origins(node.operand)
        if isinstance(node, ast.Compare):
            # identity (presence) checks are static under trace
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return EMPTY
            out = self.origins(node.left)
            for c in node.comparators:
                out |= self.origins(c)
            return out
        if isinstance(node, ast.BoolOp):
            out = EMPTY
            for v in node.values:
                out |= self.origins(v)
            return out
        if isinstance(node, ast.IfExp):
            return (self.origins(node.body) | self.origins(node.orelse)
                    | self.origins(node.test))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = EMPTY
            for e in node.elts:
                out |= self.origins(e)
            return out
        if isinstance(node, ast.Starred):
            return self.origins(node.value)
        if isinstance(node, ast.Call):
            return self.call_origins(node)
        return EMPTY

    def call_origins(self, node):
        """Origin set of a call's result.  Resolvable callees answer via
        their summary (a helper that drops its tainted argument launders
        the taint); unresolvable ones get the conservative union."""
        name = call_name(node.func)
        base = name.split(".")[-1] if name else None
        if base in SHAPE_FNS:
            return EMPTY
        arg_origins = EMPTY
        for a in node.args:
            arg_origins |= self.origins(a)
        for k in node.keywords:
            arg_origins |= self.origins(k.value)
        if isinstance(node.func, ast.Attribute):
            arg_origins |= self.origins(node.func.value)
        summary, binding = self.callee_summary(node)
        if summary is not None:
            out = EMPTY
            for origin in summary.ret_origins:
                out |= binding.get(origin, EMPTY)
            return out
        return arg_origins

    def callee_summary(self, node):
        """(Summary, {callee param -> actual-arg origin set}) for a
        resolvable call within depth, else (None, None)."""
        if self.engine is None or self.scope is None or self.depth <= 0:
            return None, None
        fi = self.engine.callgraph.resolve_call(node.func, self.scope)
        if fi is None:
            return None, None
        summary = self.engine.summary(fi, self.depth - 1)
        if summary is None:
            return None, None
        params = fi.params
        # drop the bound receiver for self.method(...) calls
        if params and params[0] == "self" and isinstance(
                node.func, ast.Attribute):
            params = params[1:]
        binding = {}
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Starred):
                break
            if i < len(params):
                binding[params[i]] = self.origins(a)
        for kw in node.keywords:
            if kw.arg is not None:
                binding[kw.arg] = self.origins(kw.value)
        return summary, binding

    # ---- propagation -----------------------------------------------------

    def _bind(self, target, origins):
        if isinstance(target, ast.Name):
            if origins:
                self.env[target.id] = origins
            else:
                self.env.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, origins)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, origins)

    def visit_Assign(self, node):
        self.visit(node.value)
        o = self.origins(node.value)
        for tgt in node.targets:
            self._bind(tgt, o)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        o = self.origins(node.value)
        if o and isinstance(node.target, ast.Name):
            self.env[node.target.id] = self.env.get(node.target.id,
                                                    EMPTY) | o

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
            self._bind(node.target, self.origins(node.value))

    def visit_For(self, node):
        self.visit(node.iter)
        self._bind(node.target, self.origins(node.iter))
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_Return(self, node):
        if node.value is not None:
            self.visit(node.value)
            self.ret_origins |= self.origins(node.value)

    def visit_Call(self, node):
        self.on_call(node)
        self.generic_visit(node)

    def on_call(self, node):  # hazard hook — subclasses override
        pass

    def instantiate_callee_hazards(self, node):
        """Fold a resolvable callee's hazards into this summary: each
        hazard re-anchors at this call site with its origin set mapped
        through the argument binding (a hazard whose origins bind to
        concrete actuals is dead at this site and dropped)."""
        summary, binding = self.callee_summary(node)
        if summary is None:
            return
        for hz in summary.hazards:
            if not hz.origins:
                self.hazards.append(Hazard(EMPTY, hz.rule, hz.message,
                                           node.lineno))
                continue
            origins = EMPTY
            for o in hz.origins:
                origins |= binding.get(o, EMPTY)
            if origins:
                self.hazards.append(Hazard(origins, hz.rule, hz.message,
                                           node.lineno))

    # Closures share the enclosing frame's taint env (they see outer
    # locals); parameters of the nested def shadow nothing tainted.
    def visit_FunctionDef(self, node):
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


class SummaryEngine:
    """Memoized per-function summaries for one walker class.

    ``make_walker(engine, fi, depth)`` builds the rule's OriginWalker
    subclass; the engine seeds the walker's env with each parameter as
    its own origin, walks the body, and caches the resulting Summary
    keyed on (function, depth).  Recursion is broken by registering an
    in-progress marker that resolves to the opaque summary.
    """

    def __init__(self, callgraph, make_walker, max_depth=2):
        self.callgraph = callgraph
        self.make_walker = make_walker
        self.max_depth = max_depth
        self._memo = {}
        self._in_progress = set()

    def summary(self, fi, depth=None):
        depth = self.max_depth if depth is None else depth
        if depth <= 0 or id(fi.node) in self._in_progress:
            return Summary.opaque(p for p in fi.params if p != "self")
        key = (id(fi.node), depth)
        if key in self._memo:
            return self._memo[key]
        self._in_progress.add(id(fi.node))
        try:
            w = self.make_walker(self, fi, depth)
            for p in fi.params:
                if p != "self":
                    w.env[p] = frozenset((p,))
            for stmt in fi.node.body:
                w.visit(stmt)
            s = Summary(hazards=w.hazards, ret_origins=w.ret_origins)
        finally:
            self._in_progress.discard(id(fi.node))
        self._memo[key] = s
        return s
