"""Sharding lint: mesh-axis vocabulary and Pallas out-sharding pinning.

Two rules, both encoding GSPMD failure modes that are silent at runtime:

``shard-axis``
    Every string literal passed to ``PartitionSpec(...)`` / ``P(...)`` /
    ``NamedSharding(...)`` must be a mesh axis declared in
    ``parallel/mesh.py`` (``AXIS_* = "..."`` constants).  A typo'd axis
    name raises only when the spec first meets a real mesh — i.e. on the
    TPU pod, not under the CPU test harness's 8 fake devices, and logical
    axis names from sharding *rules* pass through translation maps that
    can silently drop them.

``shard-pallas-out-shardings``
    A ``jax.jit`` call that pins ``in_shardings`` but not ``out_shardings``
    while (transitively, within the module, plus repo-wide Pallas entry
    points) calling a ``pallas_call`` kernel is exactly the bug PR 1 fixed
    by hand in ``parallel/train.py``: ``pallas_call`` lowers to a custom
    call GSPMD cannot partition, so the output sharding silently falls back
    to replicated and every step pays an all-gather.
"""
from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .tracer import _call_name

_SPEC_NAMES = {"PartitionSpec", "P", "NamedSharding"}


def _axis_literals(call):
    """Yield (string, lineno) axis-name literals in a spec constructor call,
    looking through tuple arguments (PartitionSpec(("dp", "fsdp"), None))."""
    for arg in call.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            yield arg.value, arg.lineno
        elif isinstance(arg, (ast.Tuple, ast.List)):
            for e in arg.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    yield e.value, e.lineno


@register
class ShardAxisRule(Rule):
    name = "shard-axis"
    description = ("PartitionSpec/NamedSharding axis-name literal not "
                   "declared in parallel/mesh.py")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        axes = ctx.project.mesh_axes if ctx.project is not None else set()
        if not axes:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name is None or name.split(".")[-1] not in _SPEC_NAMES:
                continue
            for axis, lineno in _axis_literals(node):
                if axis not in axes:
                    yield Finding(
                        ctx.path, lineno, self.name,
                        f"unknown mesh axis {axis!r} in "
                        f"{name.split('.')[-1]}(...) — parallel/mesh.py "
                        f"declares {', '.join(sorted(axes))}")


def _jit_applications(tree):
    """Yield (FunctionDef, keywords, lineno) for every jit/pjit application
    in the module whose target function is resolvable: decorator forms
    (@jax.jit, @partial(jax.jit, ...)) and wrapping calls (jax.jit(f, ...))."""
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    name = _call_name(dec.func)
                    if name and name.split(".")[-1] in ("jit", "pjit"):
                        yield node, dec.keywords, dec.lineno
                    elif (name and name.split(".")[-1] == "partial"
                          and dec.args
                          and (_call_name(dec.args[0]) or "").split(".")[-1]
                          in ("jit", "pjit")):
                        yield node, dec.keywords, dec.lineno
        elif isinstance(node, ast.Call):
            name = _call_name(node.func)
            if (name and name.split(".")[-1] in ("jit", "pjit")
                    and node.args and isinstance(node.args[0], ast.Name)):
                target = defs.get(node.args[0].id)
                if target is not None:
                    yield target, node.keywords, node.lineno


def _reaches_pallas(fn, defs, pallas_entries, _seen=None):
    """Module-local transitive reachability from ``fn`` to a pallas_call or
    to a repo-wide Pallas entry-point name; returns the callee name hit."""
    if _seen is None:
        _seen = set()
    if fn.name in _seen:
        return None
    _seen.add(fn.name)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name is None:
            continue
        base = name.split(".")[-1]
        if base == "pallas_call":
            return "pallas_call"
        if base in pallas_entries:
            return base
        if base in defs and defs[base] is not fn:
            hit = _reaches_pallas(defs[base], defs, pallas_entries, _seen)
            if hit:
                return hit
    return None


@register
class ShardPallasOutShardingsRule(Rule):
    name = "shard-pallas-out-shardings"
    description = ("sharded jit (in_shardings set) reaching a Pallas kernel "
                   "without out_shardings — GSPMD cannot partition the "
                   "custom call (PR 1 pinning lesson)")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        entries = ctx.project.pallas_entries if ctx.project is not None else set()
        defs = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        for fn, keywords, lineno in _jit_applications(ctx.tree):
            kws = {kw.arg for kw in keywords if kw.arg}
            if "in_shardings" not in kws or "out_shardings" in kws:
                continue
            hit = _reaches_pallas(fn, defs, entries)
            if hit:
                yield Finding(
                    ctx.path, lineno, self.name,
                    f"jit of '{fn.name}' pins in_shardings but not "
                    f"out_shardings while calling Pallas kernel '{hit}'; "
                    "pallas_call is a custom call GSPMD cannot partition — "
                    "pin the outputs (out_shardings=...) or the result "
                    "silently falls back to replicated")
