"""Host-sync analyzer: device->host synchronization inside annotated
serving hot paths.

The async decode engine (``serve.ContinuousBatcher``) splits work across
a device thread (dispatch, keeps >=2 steps in flight) and a host thread
(drains readback chunks).  The whole point of the split is that the
device thread NEVER blocks on device values: a stray
``block_until_ready()``, ``.item()``, ``float(x)`` or ``np.asarray(x)``
in the dispatch path serializes the pipeline back into the single-thread
engine this PR replaced — silently, with no test failure, just a
throughput regression.  This rule machine-enforces the invariant.

Unlike the tracer rules (which find jit-staged functions by decorator),
the hot path is *host* code: there is nothing syntactic to key off, so
functions opt in with a marker comment on (or directly above) the
``def`` line::

    def _dispatch(self):  # graftcheck: hotpath
        ...

Inside a marked function the rule flags

- ``.block_until_ready()`` / ``.item()`` / ``.tolist()`` / ``.numpy()``
  / ``.to_py()`` method calls (explicit host syncs),
- ``np.asarray(...)`` and friends (implicit ``__array__`` sync),
- ``float()`` / ``int()`` / ``bool()`` / ``complex()`` on anything not
  provably static (shape/dtype/len chains and literals are exempt —
  ``int(rows.shape[0])`` is metadata, not a readback).

``copy_to_host_async`` is deliberately NOT flagged: it is the
non-blocking transfer the engine is built around.  Nested functions
inherit the enclosing marker (a closure defined in the hot path runs in
the hot path).  Escape hatch for a justified sync: the standard
``# graftcheck: disable=hostsync`` suppression on the offending line.
"""
from __future__ import annotations

import ast
import re

from .core import Finding, Rule, register
from .tracer import _CAST_FNS, _HOST_METHODS, _NUMPY_FORCERS, _NUMPY_ROOTS, _call_name

_HOTPATH_RE = re.compile(r"#\s*graftcheck:\s*hotpath\b")

# Blocking syncs beyond tracer.py's _HOST_METHODS; copy_to_host_async is
# the sanctioned non-blocking cousin and stays legal.
_SYNC_METHODS = _HOST_METHODS | {"block_until_ready"}

# Attribute chains that read array *metadata* (host-resident already, no
# device sync) — int(x.shape[0]) and friends are exempt.
_META_ATTRS = {"shape", "ndim", "size", "dtype"}
# Builtins whose result is a plain Python value regardless of argument.
_STATIC_FNS = {"len", "range", "min", "max", "sum", "round", "ord", "id"}


def _is_static(node):
    """True when ``node`` provably evaluates to a host-side Python value
    (so casting it is free).  Conservative: a bare name could hold
    anything, so it is NOT static — in a marked hot path the burden of
    proof is on the code."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _META_ATTRS or _is_static(node.value)
    if isinstance(node, ast.Subscript):
        return _is_static(node.value)
    if isinstance(node, ast.UnaryOp):
        return _is_static(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_static(node.left) and _is_static(node.right)
    if isinstance(node, ast.Call):
        name = _call_name(node.func)
        base = name.split(".")[-1] if name else None
        return base in _STATIC_FNS
    return False


class _HotpathWalker(ast.NodeVisitor):
    def __init__(self, ctx, fn):
        self.ctx = ctx
        self.fn = fn
        self.findings = []

    def _flag(self, node, msg):
        self.findings.append(Finding(self.ctx.path, node.lineno,
                                     "hostsync", msg))

    def visit_Call(self, node):
        name = _call_name(node.func)
        base = name.split(".")[-1] if name else None

        if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
            self._flag(node,
                       f".{node.func.attr}() blocks on a device value inside "
                       f"hot path '{self.fn.name}'; move the sync to the host "
                       "thread (or use copy_to_host_async)")
        elif (name is not None and "." in name
              and name.split(".")[0] in _NUMPY_ROOTS
              and base in _NUMPY_FORCERS):
            self._flag(node,
                       f"{name}() forces a synchronous device->host copy "
                       f"inside hot path '{self.fn.name}'; keep the array on "
                       "device and convert in the host thread")
        elif (base in _CAST_FNS and name == base and node.args
              and not all(_is_static(a) for a in node.args)):
            self._flag(node,
                       f"{base}() on a possibly-device value inside hot path "
                       f"'{self.fn.name}' forces a blocking readback; shape/"
                       "dtype metadata is exempt, device values are not")
        self.generic_visit(node)

    # Closures defined inside a hot path run inside the hot path.
    def visit_FunctionDef(self, node):
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def _is_marked(ctx, fn):
    """Marker on the ``def`` line itself or the line directly above
    (which may also be a decorator line — both read naturally)."""
    for lineno in (fn.lineno, fn.lineno - 1):
        if 1 <= lineno <= len(ctx.lines) and _HOTPATH_RE.search(ctx.lines[lineno - 1]):
            return True
    return False


@register
class HostSyncRule(Rule):
    name = "hostsync"
    description = ("blocking device sync (block_until_ready/.item()/float()/"
                   "np.asarray) inside a '# graftcheck: hotpath' function")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        marked = [node for node in ast.walk(ctx.tree)
                  if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and _is_marked(ctx, node)]
        # A function nested inside a marked function is already covered by
        # the closure walk — walking it again would double-report.
        nested = set()
        for fn in marked:
            for sub in ast.walk(fn):
                if sub is not fn and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested.add(id(sub))
        for fn in marked:
            if id(fn) in nested:
                continue
            w = _HotpathWalker(ctx, fn)
            for stmt in fn.body:
                w.visit(stmt)
            yield from w.findings
