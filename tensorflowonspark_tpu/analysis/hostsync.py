"""Host-sync analyzer: device->host synchronization in serving hot paths.

The async decode engine (``serve.ContinuousBatcher``) splits work across
a device thread (dispatch, keeps >=2 steps in flight) and a host thread
(drains readback chunks).  The whole point of the split is that the
device thread NEVER blocks on device values: a stray
``block_until_ready()``, ``.item()``, ``float(x)`` or ``np.asarray(x)``
in the dispatch path serializes the pipeline back into the single-thread
engine PR 6 replaced — silently, with no test failure, just a
throughput regression.  This rule machine-enforces the invariant.

**Which functions are hot paths?**  Two sources, merged:

- **Inferred** (the default since graftcheck v2): the thread-role map
  (:mod:`.threads`) marks a thread role as the *device-dispatch role*
  when its call closure starts device copies (``copy_to_host_async``);
  every method reachable ONLY from that role is a hot path — zero
  annotations.  Methods also reachable from the host/external roles
  (``_process_batch``, ``_retire``, ...) are shared host-side code and
  are exempt.
- **Marked**: the legacy ``# graftcheck: hotpath`` comment on (or
  directly above) the ``def`` line still works for host code the role
  inference cannot see (free functions, single-threaded drivers) and
  runs the STRICTER cast check below.

Inside a hot function the rule flags

- ``.block_until_ready()`` / ``.item()`` / ``.tolist()`` / ``.numpy()``
  / ``.to_py()`` method calls (explicit host syncs) — including inside
  project helpers the hot function calls (call-graph summaries via
  :mod:`.dataflow`: the finding lands at the hot call site and names
  the helper line),
- ``np.asarray(...)`` and friends (implicit ``__array__`` sync),
- ``float()`` / ``int()`` / ``bool()`` / ``complex()`` on non-static
  arguments.  Marked functions use the strict test (a bare name could
  hold anything — the marker shifts the burden of proof onto the code);
  inferred functions relax it so plain host-int locals
  (``bool(stops)``, ``float(t1 - t0)``) pass, and only expressions
  containing calls or object attribute loads — the shapes a device
  array actually arrives in — are flagged.

``copy_to_host_async`` is deliberately NOT flagged: it is the
non-blocking transfer the engine is built around.  Nested functions
inherit the enclosing hot status (a closure defined in the hot path
runs in the hot path).  Escape hatch for a justified sync: the standard
``# graftcheck: disable=hostsync`` suppression on the offending line.
"""
from __future__ import annotations

import ast
import re

from . import threads
from .core import Finding, Rule, register
from .dataflow import EMPTY, Hazard, OriginWalker, SummaryEngine
from .tracer import (_CAST_FNS, _HOST_METHODS, _NUMPY_FORCERS, _NUMPY_ROOTS,
                     _call_name)
from . import callgraph as callgraph_mod

_HOTPATH_RE = re.compile(r"#\s*graftcheck:\s*hotpath\b")

# Blocking syncs beyond tracer.py's _HOST_METHODS; copy_to_host_async is
# the sanctioned non-blocking cousin and stays legal.
_SYNC_METHODS = _HOST_METHODS | {"block_until_ready"}

# Attribute chains that read array *metadata* (host-resident already, no
# device sync) — int(x.shape[0]) and friends are exempt.
_META_ATTRS = {"shape", "ndim", "size", "dtype"}
# Builtins whose result is a plain Python value regardless of argument.
_STATIC_FNS = {"len", "range", "min", "max", "sum", "round", "ord", "id"}


def _is_static(node, relaxed=False):
    """True when ``node`` provably evaluates to a host-side Python value
    (so casting it is free).  Strict mode: a bare name could hold
    anything, so it is NOT static.  Relaxed mode (role-inferred hot
    paths): bare names and boolean combinations pass — only calls and
    non-metadata attribute loads look like device values."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return relaxed
    if isinstance(node, ast.Attribute):
        return node.attr in _META_ATTRS or _is_static(node.value, relaxed)
    if isinstance(node, ast.Subscript):
        return _is_static(node.value, relaxed)
    if isinstance(node, ast.UnaryOp):
        return _is_static(node.operand, relaxed)
    if isinstance(node, ast.BinOp):
        return (_is_static(node.left, relaxed)
                and _is_static(node.right, relaxed))
    if isinstance(node, (ast.BoolOp, ast.Compare)) and relaxed:
        parts = (node.values if isinstance(node, ast.BoolOp)
                 else [node.left] + node.comparators)
        return all(_is_static(p, relaxed) for p in parts)
    if isinstance(node, ast.Call):
        name = _call_name(node.func)
        base = name.split(".")[-1] if name else None
        return base in _STATIC_FNS
    return False


class _SyncOriginWalker(OriginWalker):
    """Summary walker for helper functions: records the unconditionally
    blocking operations (explicit sync methods) so hot callers report
    them at the call site.  Casts/np.asarray stay intra-function — in a
    helper they are usually legitimate host-side conversions."""

    def on_call(self, node):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS:
            self.hazards.append(Hazard(
                EMPTY, "hostsync",
                f".{node.func.attr}() blocks on a device value",
                node.lineno))
        else:
            self.instantiate_callee_hazards(node)


def _sync_engine(ctx):
    project = ctx.project
    if project is None or not getattr(project, "files", None):
        return None
    engine = getattr(project, "_hostsync_engine", None)
    if engine is None:
        cg = callgraph_mod.for_project(project)
        if not cg.modules:
            return None
        engine = SummaryEngine(
            cg, lambda e, fi, depth: _SyncOriginWalker(e, fi, depth))
        project._hostsync_engine = engine
    return engine


class _HotpathWalker(ast.NodeVisitor):
    def __init__(self, ctx, fn, strict, engine=None, hot_ids=()):
        self.ctx = ctx
        self.fn = fn
        self.strict = strict
        self.engine = engine
        self.hot_ids = hot_ids
        self.findings = []

    def _flag(self, node, msg):
        self.findings.append(Finding(self.ctx.path, node.lineno,
                                     "hostsync", msg))

    def visit_Call(self, node):
        name = _call_name(node.func)
        base = name.split(".")[-1] if name else None

        if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
            self._flag(node,
                       f".{node.func.attr}() blocks on a device value inside "
                       f"hot path '{self.fn.name}'; move the sync to the host "
                       "thread (or use copy_to_host_async)")
        elif (name is not None and "." in name
              and name.split(".")[0] in _NUMPY_ROOTS
              and base in _NUMPY_FORCERS):
            self._flag(node,
                       f"{name}() forces a synchronous device->host copy "
                       f"inside hot path '{self.fn.name}'; keep the array on "
                       "device and convert in the host thread")
        elif (base in _CAST_FNS and name == base and node.args
              and not all(_is_static(a, relaxed=not self.strict)
                          for a in node.args)):
            self._flag(node,
                       f"{base}() on a possibly-device value inside hot path "
                       f"'{self.fn.name}' forces a blocking readback; shape/"
                       "dtype metadata is exempt, device values are not")
        elif self.engine is not None:
            self._check_callee(node)
        self.generic_visit(node)

    def _check_callee(self, node):
        cg = self.engine.callgraph
        scope = cg.function_info(self.fn)
        if scope is None:
            return
        fi = cg.resolve_call(node.func, scope)
        if fi is None or id(fi.node) in self.hot_ids:
            return      # hot callees are checked directly at their def
        for hz in self.engine.summary(fi).hazards:
            self._flag(node,
                       f"{hz.message} in helper '{fi.name}' (line {hz.line})"
                       f" called from hot path '{self.fn.name}'; move the "
                       "sync to the host thread")

    # Closures defined inside a hot path run inside the hot path.
    def visit_FunctionDef(self, node):
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def _is_marked(ctx, fn):
    """Marker on the ``def`` line itself or the line directly above
    (which may also be a decorator line — both read naturally)."""
    for lineno in (fn.lineno, fn.lineno - 1):
        if 1 <= lineno <= len(ctx.lines) and _HOTPATH_RE.search(ctx.lines[lineno - 1]):
            return True
    return False


@register
class HostSyncRule(Rule):
    name = "hostsync"
    description = ("blocking device sync (block_until_ready/.item()/float()/"
                   "np.asarray) inside a device-role-inferred or "
                   "'# graftcheck: hotpath'-marked function")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        marked = [node for node in ast.walk(ctx.tree)
                  if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and _is_marked(ctx, node)]
        marked_ids = {id(fn) for fn in marked}
        inferred = [fn for fid, fn in
                    sorted(threads.inferred_hotpaths(ctx).items())
                    if fid not in marked_ids]
        hot = [(fn, True) for fn in marked] + [(fn, False) for fn in inferred]
        # A function nested inside a hot function is already covered by
        # the closure walk — walking it again would double-report.
        nested = set()
        for fn, _strict in hot:
            for sub in ast.walk(fn):
                if sub is not fn and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested.add(id(sub))
        engine = _sync_engine(ctx)
        hot_ids = {id(fn) for fn, _strict in hot}
        for fn, strict in hot:
            if id(fn) in nested:
                continue
            w = _HotpathWalker(ctx, fn, strict, engine=engine,
                               hot_ids=hot_ids)
            for stmt in fn.body:
                w.visit(stmt)
            yield from w.findings
