"""Style tier: the checks scripts/lint.py used to own, re-homed on the
shared graftcheck walker.

The unused-import rule is the one with real logic: the old linter's
"name appears at most once in the raw source" heuristic both missed
genuinely dead imports (any textual mention — a docstring, a comment —
kept them alive) and flagged names used only through ``__all__`` or string
annotations.  This version tracks actual ``Name`` loads plus the two
string-shaped usage channels: entries in ``__all__`` and identifiers
inside string (forward-reference) annotations.
"""
from __future__ import annotations

import ast
import re

from .core import Finding, Rule, register, _posix

MAX_LINE = 160

_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


@register
class LineLengthRule(Rule):
    name = "line-too-long"
    description = f"line exceeds {MAX_LINE} characters"
    scope = "all"
    kind = "style"

    def check(self, ctx):
        for i, ln in enumerate(ctx.lines, start=1):
            if len(ln) > MAX_LINE:
                yield Finding(ctx.path, i, self.name,
                              f"line too long ({len(ln)} > {MAX_LINE})")


@register
class TrailingWhitespaceRule(Rule):
    name = "trailing-whitespace"
    description = "line ends with whitespace"
    scope = "all"
    kind = "style"

    def check(self, ctx):
        for i, ln in enumerate(ctx.lines, start=1):
            if ln != ln.rstrip():
                yield Finding(ctx.path, i, self.name, "trailing whitespace")


@register
class TabIndentRule(Rule):
    name = "tab-indent"
    description = "indentation uses tab characters"
    scope = "all"
    kind = "style"

    def check(self, ctx):
        for i, ln in enumerate(ctx.lines, start=1):
            if ln.startswith("\t"):
                yield Finding(ctx.path, i, self.name, "tab indentation")


@register
class DebuggerCallRule(Rule):
    name = "debugger-call"
    description = "breakpoint()/pdb.set_trace() left in code"
    scope = "all"
    kind = "style"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "breakpoint":
                yield Finding(ctx.path, node.lineno, self.name,
                              "breakpoint() call")
            elif (isinstance(fn, ast.Attribute) and fn.attr == "set_trace"
                  and isinstance(fn.value, ast.Name)
                  and fn.value.id in ("pdb", "ipdb")):
                yield Finding(ctx.path, node.lineno, self.name,
                              f"{fn.value.id}.set_trace() call")


# Network / recovery-path modules where a swallowed exception can turn a
# transient fault into a silent hang or a stranded session.  Crash
# recovery (fleet re-drive, migration rollback) DEPENDS on failures
# propagating to the layer that journals and retries them.
_RECOVERY_MODULES = frozenset({
    "reservation.py", "fleet.py", "fleet_client.py", "kvtransfer.py",
    "serve.py", "faults.py",
})


@register
class SwallowedNetworkErrorRule(Rule):
    name = "swallowed-network-error"
    description = ("bare `except:`/`except Exception:` with a pass-only "
                   "body in a network/recovery module")
    scope = "package"
    kind = "semantic"

    def _broad(self, handler):
        t = handler.type
        if t is None:
            return True
        return isinstance(t, ast.Name) and t.id in ("Exception",
                                                    "BaseException")

    def check(self, ctx):
        fname = _posix(ctx.path).rsplit("/", 1)[-1]
        if fname not in _RECOVERY_MODULES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._broad(node):
                continue
            body = [s for s in node.body
                    if not (isinstance(s, ast.Expr)
                            and isinstance(s.value, ast.Constant))]
            if all(isinstance(s, ast.Pass) for s in body):
                yield Finding(
                    ctx.path, node.lineno, self.name,
                    "broad except with pass-only body swallows "
                    "network/recovery failures — narrow the exception "
                    "or log and re-raise")


class _UsageVisitor(ast.NodeVisitor):
    """Collects imported names, loaded names, ``__all__`` entries, and
    identifiers appearing inside string annotations."""

    def __init__(self):
        self.imports = []      # (name, lineno, statement)
        self.used = set()
        self.exported = set()  # names in __all__
        self.string_ann = set()

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports.append((name, node.lineno, f"import {a.name}"))

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            name = a.asname or a.name
            self.imports.append(
                (name, node.lineno,
                 f"from {'.' * node.level}{node.module or ''} import {a.name}"))

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        self.generic_visit(node)

    def visit_Assign(self, node):
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                self.exported.update(self._str_elts(node.value))
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name) and node.target.id == "__all__":
            self.exported.update(self._str_elts(node.value))
        self.generic_visit(node)

    @staticmethod
    def _str_elts(value):
        if isinstance(value, (ast.List, ast.Tuple)):
            for e in value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    yield e.value

    def _string_annotation(self, ann):
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            self.string_ann.update(_IDENT_RE.findall(ann.value))

    def visit_AnnAssign(self, node):
        self._string_annotation(node.annotation)
        self.generic_visit(node)

    def visit_arg(self, node):
        if node.annotation is not None:
            self._string_annotation(node.annotation)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if node.returns is not None:
            self._string_annotation(node.returns)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


@register
class UnusedImportRule(Rule):
    name = "unused-import"
    description = "imported name never loaded (checks __all__ and string annotations)"
    scope = "all"
    kind = "style"

    def check(self, ctx):
        v = _UsageVisitor()
        v.visit(ctx.tree)
        alive = v.used | v.exported | v.string_ann
        for name, lineno, stmt in v.imports:
            if name.startswith("_"):
                continue
            if name not in alive:
                yield Finding(ctx.path, lineno, self.name,
                              f"unused import: {stmt!r} binds {name!r}")
