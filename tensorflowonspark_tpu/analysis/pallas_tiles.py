"""Pallas tile lint: BlockSpec tile-shape alignment and interpret fallbacks.

``pallas-tile``
    TPU vector memory moves (sublane × lane) tiles: the minor dimension in
    units of 128 lanes and the second-minor in dtype-dependent sublanes
    (8 for f32, 16 for bf16, 32 for int8).  A ``BlockSpec`` block shape
    whose literal minor dim is not a multiple of 128 (or second-minor not a
    multiple of 8, the f32 floor) compiles — Mosaic pads — but every block
    load/store wastes the pad fraction and can force relayouts.  Only
    literal ints are checked (symbolic dims pass); specs with an explicit
    ``memory_space`` (SMEM scalar specs) are exempt.

    Two quantized-weight carve-outs (ops/quant_matmul.py):  a literal
    minor that is a multiple of 64 passes, because a nibble-packed int4
    block of 64 bytes spans a full 128 logical lanes once unpacked; and a
    literal second-minor that *divides* 8 passes (1, 2, 4), because
    per-group scale blocks carry ``block_k / group_size`` rows — a
    handful of broadcast rows, not a sublane-tiled operand (the previous
    scalar-row allowance for ``1`` is the degenerate case).

``pallas-interpret``
    Every ``pl.pallas_call`` must thread an ``interpret=`` flag.  The repo
    convention (``ops.default_interpret()``) runs kernels in interpret mode
    off-TPU so the CPU test harness exercises them; a pallas_call without
    the flag hard-fails on every machine without a TPU.

``pallas-prefetch-arity``
    Under a ``PrefetchScalarGridSpec(num_scalar_prefetch=k, grid=(...))``
    every BlockSpec index_map receives the grid coordinates PLUS the k
    scalar-prefetch refs — len(grid) + k arguments.  A lambda written for
    the plain-GridSpec arity (grid coordinates only) fails at trace time
    with an opaque arity TypeError deep inside pallas; the lint names the
    lambda and the expected count instead.  Checked per enclosing
    function when it builds exactly one PrefetchScalarGridSpec with a
    literal ``num_scalar_prefetch`` and a literal grid tuple (the repo
    idiom — ops/paged_attention.py, ops/paged_prefill.py); index_maps
    given as local ``def``s are resolved too, ``*args`` signatures pass.
"""
from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .tracer import _call_name

_LANE = 128
_HALF_LANE = 64  # nibble-packed int4: 64 bytes = 128 logical lanes
_SUBLANE = 8  # f32 floor; bf16 wants 16, int8 wants 32


def _literal_dims(arg):
    """Block-shape tuple -> list of (value_or_None, lineno)."""
    if not isinstance(arg, (ast.Tuple, ast.List)):
        return None
    dims = []
    for e in arg.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            dims.append((e.value, e.lineno))
        else:
            dims.append((None, getattr(e, "lineno", arg.lineno)))
    return dims


@register
class PallasTileRule(Rule):
    name = "pallas-tile"
    description = ("BlockSpec literal block shape not a multiple of the "
                   "dtype tile (8x128 f32 / 16x128 bf16 / 32x128 int8)")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name is None or name.split(".")[-1] != "BlockSpec":
                continue
            if any(kw.arg == "memory_space" for kw in node.keywords):
                continue  # SMEM/ANY scalar specs are not vector-tiled
            if not node.args:
                continue
            dims = _literal_dims(node.args[0])
            if not dims:
                continue
            minor, minor_line = dims[-1]
            if (minor is not None and minor % _LANE != 0
                    and minor % _HALF_LANE != 0):
                yield Finding(
                    ctx.path, minor_line, self.name,
                    f"BlockSpec minor dim {minor} is not a multiple of "
                    f"{_LANE} (TPU lane width; {_HALF_LANE} allowed for "
                    "nibble-packed int4 blocks); Mosaic pads every block "
                    "load/store to the full tile")
            if len(dims) >= 2:
                sub, sub_line = dims[-2]
                if (sub is not None and sub % _SUBLANE != 0
                        and (sub <= 0 or _SUBLANE % sub != 0)):
                    yield Finding(
                        ctx.path, sub_line, self.name,
                        f"BlockSpec second-minor dim {sub} is not a multiple "
                        f"of {_SUBLANE} (f32 sublane; bf16 needs 16, int8 "
                        "needs 32) nor a divisor of it (grouped-scale rows)")


def _prefetch_arity(call):
    """PrefetchScalarGridSpec call -> len(grid) + num_scalar_prefetch,
    or None when either is not literal enough to know."""
    k = grid = None
    for kw in call.keywords:
        if kw.arg == "num_scalar_prefetch":
            if not (isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, int)):
                return None
            k = kw.value.value
        elif kw.arg == "grid":
            if not isinstance(kw.value, (ast.Tuple, ast.List)):
                return None
            grid = len(kw.value.elts)
    if k is None or grid is None:
        return None
    return grid + k


def _index_map_params(arg, local_defs):
    """index_map argument -> (n_params, lineno), or None when the arity
    cannot be known statically (*args, non-local callables, partials)."""
    if isinstance(arg, ast.Lambda):
        a = arg.args
        if a.vararg is not None:
            return None
        return (len(a.posonlyargs) + len(a.args), arg.lineno)
    if isinstance(arg, ast.Name) and arg.id in local_defs:
        fn = local_defs[arg.id]
        a = fn.args
        if a.vararg is not None:
            return None
        return (len(a.posonlyargs) + len(a.args), arg.lineno)
    return None


@register
class PallasPrefetchArityRule(Rule):
    name = "pallas-prefetch-arity"
    description = ("BlockSpec index_map arity does not match the "
                   "enclosing PrefetchScalarGridSpec (len(grid) + "
                   "num_scalar_prefetch arguments)")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            expected = set()
            local_defs = {}
            specs = []
            for node in ast.walk(func):
                if node is not func and isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    local_defs[node.name] = node
                if not isinstance(node, ast.Call):
                    continue
                last = (_call_name(node.func) or "").split(".")[-1]
                if last == "PrefetchScalarGridSpec":
                    expected.add(_prefetch_arity(node))
                elif last == "BlockSpec":
                    specs.append(node)
            # only a single unambiguous literal grid spec pins the arity
            # (zero or several leave the expectation unknown — pass)
            if len(expected) != 1 or None in expected:
                continue
            want = expected.pop()
            for spec in specs:
                arg = None
                if len(spec.args) >= 2:
                    arg = spec.args[1]
                else:
                    for kw in spec.keywords:
                        if kw.arg == "index_map":
                            arg = kw.value
                if arg is None:
                    continue
                got = _index_map_params(arg, local_defs)
                if got is None or got[0] == want:
                    continue
                yield Finding(
                    ctx.path, got[1], self.name,
                    f"index_map takes {got[0]} args but the enclosing "
                    f"PrefetchScalarGridSpec passes {want} (len(grid) + "
                    "num_scalar_prefetch); the missing scalar-prefetch "
                    "refs fail at trace time with a bare arity TypeError")


@register
class PallasInterpretRule(Rule):
    name = "pallas-interpret"
    description = ("pallas_call without an interpret= fallback flag — "
                   "kernel cannot run on the CPU test harness")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name is None or name.split(".")[-1] != "pallas_call":
                continue
            if any(kw.arg == "interpret" for kw in node.keywords):
                continue
            yield Finding(
                ctx.path, node.lineno, self.name,
                "pallas_call without interpret=; thread "
                "ops.default_interpret() so the kernel runs (interpreted) "
                "off-TPU — otherwise it fails on every non-TPU host")
