"""Pallas tile lint: BlockSpec tile-shape alignment and interpret fallbacks.

``pallas-tile``
    TPU vector memory moves (sublane × lane) tiles: the minor dimension in
    units of 128 lanes and the second-minor in dtype-dependent sublanes
    (8 for f32, 16 for bf16, 32 for int8).  A ``BlockSpec`` block shape
    whose literal minor dim is not a multiple of 128 (or second-minor not a
    multiple of 8, the f32 floor) compiles — Mosaic pads — but every block
    load/store wastes the pad fraction and can force relayouts.  Only
    literal ints are checked (symbolic dims pass); a literal ``1``
    second-minor is allowed (scalar rows); specs with an explicit
    ``memory_space`` (SMEM scalar specs) are exempt.

``pallas-interpret``
    Every ``pl.pallas_call`` must thread an ``interpret=`` flag.  The repo
    convention (``ops.default_interpret()``) runs kernels in interpret mode
    off-TPU so the CPU test harness exercises them; a pallas_call without
    the flag hard-fails on every machine without a TPU.
"""
from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .tracer import _call_name

_LANE = 128
_SUBLANE = 8  # f32 floor; bf16 wants 16, int8 wants 32


def _literal_dims(arg):
    """Block-shape tuple -> list of (value_or_None, lineno)."""
    if not isinstance(arg, (ast.Tuple, ast.List)):
        return None
    dims = []
    for e in arg.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            dims.append((e.value, e.lineno))
        else:
            dims.append((None, getattr(e, "lineno", arg.lineno)))
    return dims


@register
class PallasTileRule(Rule):
    name = "pallas-tile"
    description = ("BlockSpec literal block shape not a multiple of the "
                   "dtype tile (8x128 f32 / 16x128 bf16 / 32x128 int8)")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name is None or name.split(".")[-1] != "BlockSpec":
                continue
            if any(kw.arg == "memory_space" for kw in node.keywords):
                continue  # SMEM/ANY scalar specs are not vector-tiled
            if not node.args:
                continue
            dims = _literal_dims(node.args[0])
            if not dims:
                continue
            minor, minor_line = dims[-1]
            if minor is not None and minor % _LANE != 0:
                yield Finding(
                    ctx.path, minor_line, self.name,
                    f"BlockSpec minor dim {minor} is not a multiple of "
                    f"{_LANE} (TPU lane width); Mosaic pads every block "
                    "load/store to the full tile")
            if len(dims) >= 2:
                sub, sub_line = dims[-2]
                if sub is not None and sub != 1 and sub % _SUBLANE != 0:
                    yield Finding(
                        ctx.path, sub_line, self.name,
                        f"BlockSpec second-minor dim {sub} is not a multiple "
                        f"of {_SUBLANE} (f32 sublane; bf16 needs 16, int8 "
                        "needs 32)")


@register
class PallasInterpretRule(Rule):
    name = "pallas-interpret"
    description = ("pallas_call without an interpret= fallback flag — "
                   "kernel cannot run on the CPU test harness")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name is None or name.split(".")[-1] != "pallas_call":
                continue
            if any(kw.arg == "interpret" for kw in node.keywords):
                continue
            yield Finding(
                ctx.path, node.lineno, self.name,
                "pallas_call without interpret=; thread "
                "ops.default_interpret() so the kernel runs (interpreted) "
                "off-TPU — otherwise it fails on every non-TPU host")
