"""jit-cache lint: varying shapes/statics fed to traced callables.

XLA compiles one program per (shape, dtype, static-args) signature.  A
slice with a data-dependent bound — ``toks[:n]`` where ``n`` is the
request's prompt length — gives every distinct length its own
compilation, which is the compile-blowup class PR 5 hand-fixed in the
prefill engine: serving code must round such bounds through the
established bucketing idioms (``_pow2_width``/``_bucket_len``-style
helpers, padding to a config constant) so the cache stays O(log n).
The same applies to ``static_argnums``/``static_argnames`` positions:
a varying Python value there is a retrace per value by definition.

What counts as a **traced callable** at a call site (per file, syntactic):

- a function staged in this module (``@jax.jit``, ``@partial(jax.jit,
  ...)``, ``g = jit(f, ...)``) — statics are read off the ``jit`` call;
- an attribute assigned from a ``_jitted*`` factory (the repo's
  ``self._step = decode._jitted_slot_step(model)`` idiom) or from a
  direct ``jit(...)`` call.

What counts as **bucketed** (stable cache key): constants, ``self.*``
config attributes, values produced by a call whose name contains
``pow2``/``bucket``/``align``/``round``/``ceil``/``pad``, and
arithmetic/min/max over those.  Function parameters, ``len(...)``
results, and subscript loads (per-request dict fields) vary per call.
Assignments are chased through local names within the function.
"""
from __future__ import annotations

import ast
import re

from .core import Finding, Rule, register
from .dataflow import call_name
from .tracer import _staged_functions, _static_filter

_BUCKET_RE = re.compile(r"pow2|bucket|align|round|ceil|pad", re.IGNORECASE)
_FACTORY_RE = re.compile(r"(^|_)jitted", re.IGNORECASE)
_JIT_NAMES = {"jit", "pjit"}


def _is_jit_call(node):
    """(static_argnums, static_argnames) when `node` is a jit(...) /
    partial(jax.jit, ...) call expression, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node.func)
    base = name.split(".")[-1] if name else None
    if base in _JIT_NAMES:
        return _static_filter(node.keywords)
    if base == "partial" and node.args:
        inner = call_name(node.args[0].func
                          if isinstance(node.args[0], ast.Call)
                          else node.args[0])
        if inner and inner.split(".")[-1] in _JIT_NAMES:
            return _static_filter(node.keywords)
    return None


def _is_factory_call(node):
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node.func)
    return bool(name and _FACTORY_RE.search(name.split(".")[-1]))


class _Stability:
    """Classify expressions as cache-stable (bucketed) or varying,
    chasing local single-assignments inside one function."""

    def __init__(self, assigns):
        self.assigns = assigns      # name -> value expr (last wins)
        self._busy = set()

    def stable(self, node):
        if node is None or isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Attribute):
            return True             # self.cfg-style config constants
        if isinstance(node, ast.Name):
            if node.id in self._busy:
                return False
            src = self.assigns.get(node.id)
            if src is None:
                return False        # parameter / loop var / unknown
            self._busy.add(node.id)
            try:
                return self.stable(src)
            finally:
                self._busy.discard(node.id)
        if isinstance(node, ast.Call):
            name = call_name(node.func)
            base = name.split(".")[-1] if name else ""
            if _BUCKET_RE.search(base):
                return True         # the bucketing idiom itself
            if base in ("min", "max", "int"):
                return all(self.stable(a) for a in node.args)
            return False            # len(...), request-dependent helpers
        if isinstance(node, ast.BinOp):
            return self.stable(node.left) and self.stable(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.stable(node.operand)
        if isinstance(node, ast.IfExp):
            return (self.stable(node.body) and self.stable(node.orelse))
        return False                # subscripts (per-request fields), etc.


def _local_assigns(fn):
    out = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.value
    return out


def _self_attr(node):
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@register
class RecompileRule(Rule):
    name = "jit-recompile"
    description = ("traced callable fed a varying slice bound or "
                   "static_argnums value — one XLA compile per distinct "
                   "value; bucket with _pow2_width/_bucket_len or pad")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        if ctx.tree is None:
            return
        traced = {}        # callable key -> (static nums, static names)
        for fn, nums, names, _how in _staged_functions(ctx.tree):
            traced[f"name:{fn.name}"] = (nums, names)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                statics = _is_jit_call(node.value)
                for tgt in node.targets:
                    key = None
                    if isinstance(tgt, ast.Name):
                        key = f"name:{tgt.id}"
                    elif _self_attr(tgt) is not None:
                        key = f"attr:{_self_attr(tgt)}"
                    if key is None:
                        continue
                    if statics is not None:
                        traced[key] = statics
                    elif key.startswith("attr:") and \
                            _is_factory_call(node.value):
                        traced[key] = (set(), set())
        if not traced:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_fn(ctx, node, traced)

    def _check_fn(self, ctx, fn, traced):
        stab = _Stability(_local_assigns(fn))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            key = None
            if isinstance(node.func, ast.Name):
                key = f"name:{node.func.id}"
            elif _self_attr(node.func) is not None:
                key = f"attr:{_self_attr(node.func)}"
            if key not in traced:
                continue
            nums, names = traced[key]
            label = key.split(":", 1)[1]
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Subscript) and \
                            isinstance(sub.slice, ast.Slice):
                        for bound in (sub.slice.lower, sub.slice.upper):
                            if bound is not None and not stab.stable(bound):
                                yield Finding(
                                    ctx.path, sub.lineno, self.name,
                                    f"slice bound fed to traced callable "
                                    f"'{label}' varies per call — every "
                                    "distinct length compiles a new XLA "
                                    "program; round it through a "
                                    "bucketing helper (_pow2_width/"
                                    "_bucket_len) or pad to a constant")
            for i in sorted(nums):
                if i < len(node.args) and not stab.stable(node.args[i]):
                    yield Finding(
                        ctx.path, node.args[i].lineno, self.name,
                        f"argument {i} of '{label}' is static_argnums but "
                        "varies per call — each value retraces; bucket it "
                        "or make it a traced array argument")
            for kw in node.keywords:
                if kw.arg in names and not stab.stable(kw.value):
                    yield Finding(
                        ctx.path, kw.value.lineno, self.name,
                        f"keyword '{kw.arg}' of '{label}' is "
                        "static_argnames but varies per call — each value "
                        "retraces; bucket it or make it a traced array "
                        "argument")
