"""Lock-discipline analyzer for the host-side orchestration plane
(fleet.py / serve.py / reservation.py / manager.py and anything else in the
package that mixes ``threading.Lock`` with mutable shared state).

The rule: in a class whose ``__init__`` creates a lock
(``self._lock = threading.Lock()/RLock()``) *and* mutable container
attributes (``{}``/``[]``/``set()``/``deque()``/...), every **content
access** of a container that is guarded anywhere must be guarded
everywhere.  A content access is a subscript, a container-method call
(``.get/.append/.pop/.items/...``), iteration, or passing the container to
``len()``/``list()``/``sorted()``-style consumers — the operations that can
interleave with a concurrent resize.  Bare attribute *reads* of the
reference (``banks = self._banks``) are deliberately not flagged: CPython
attribute rebind is atomic and the repo leans on that (serve.py's LoRA bank
swap publishes a new list object under the lock; readers grab the
reference lock-free).

Only attributes accessed BOTH inside and outside ``with self.<lock>``
blocks are reported: a container touched exclusively by one thread (the
driver-thread free lists in serve.py) never meets a lock and stays silent;
one that is always guarded is correct; the mixed ones are the bug class
PR 1 fixed once by hand (``_lora_lock``).
"""
from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .tracer import _call_name

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}
_CONTENT_METHODS = {
    "get", "items", "keys", "values", "setdefault", "update", "pop",
    "popitem", "append", "extend", "insert", "remove", "clear", "add",
    "discard", "popleft", "appendleft", "index", "count", "copy",
}
_MUTATOR_METHODS = {
    "setdefault", "update", "pop", "popitem", "append", "extend", "insert",
    "remove", "clear", "add", "discard", "popleft", "appendleft",
}
_CONSUMER_FNS = {"len", "list", "tuple", "sorted", "set", "dict", "sum",
                 "min", "max", "any", "all", "iter", "enumerate"}


def _is_lock_ctor(value):
    if not isinstance(value, ast.Call):
        return False
    name = _call_name(value.func)
    return name is not None and name.split(".")[-1] in _LOCK_CTORS


def _is_container_init(value):
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        name = _call_name(value.func)
        return name is not None and name.split(".")[-1] in _CONTAINER_CTORS
    return False


def _self_attr(node):
    """'x' for ``self.x``, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _AccessCollector(ast.NodeVisitor):
    """Walks one method recording (attr, lineno, kind, guarded) content
    accesses of self.<container> and tracking ``with self.<lock>:`` depth."""

    def __init__(self, containers, lock_attrs):
        self.containers = containers
        self.lock_attrs = lock_attrs
        self.depth = 0
        self.accesses = []  # (attr, lineno, description, guarded, mutating)

    def _note(self, attr, node, what, mutating=False):
        if attr in self.containers:
            self.accesses.append(
                (attr, node.lineno, what, self.depth > 0, mutating))

    def visit_With(self, node):
        guards = False
        for item in node.items:
            expr = item.context_expr
            attr = _self_attr(expr)
            if attr is None and isinstance(expr, ast.Call):
                attr = _self_attr(expr.func)  # self._lock.acquire()-ish
            if attr in self.lock_attrs:
                guards = True
        for item in node.items:
            self.visit(item.context_expr)
        if guards:
            self.depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if guards:
            self.depth -= 1

    def visit_Subscript(self, node):
        attr = _self_attr(node.value)
        if attr is not None:
            self._note(attr, node, "subscript",
                       mutating=isinstance(node.ctx, (ast.Store, ast.Del)))
        self.generic_visit(node)

    def visit_Call(self, node):
        if isinstance(node.func, ast.Attribute):
            attr = _self_attr(node.func.value)
            if attr is not None and node.func.attr in _CONTENT_METHODS:
                self._note(attr, node, f".{node.func.attr}()",
                           mutating=node.func.attr in _MUTATOR_METHODS)
        name = _call_name(node.func)
        if name in _CONSUMER_FNS:
            for a in node.args:
                attr = _self_attr(a)
                if attr is not None:
                    self._note(attr, node, f"{name}()")
        self.generic_visit(node)

    def visit_For(self, node):
        attr = _self_attr(node.iter)
        if attr is not None:
            self._note(attr, node, "iteration")
        self.generic_visit(node)

    def visit_comprehension(self, node):
        attr = _self_attr(node.iter)
        if attr is not None:
            # comprehensions have no lineno; borrow the iter expression's
            self._note(attr, node.iter, "iteration")
        for child in ast.iter_child_nodes(node):
            self.visit(child)


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("mutable container guarded by a lock in some methods but "
                   "content-accessed without it in others")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx, cls):
        init = None
        for stmt in cls.body:
            if isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__":
                init = stmt
                break
        if init is None:
            return

        lock_attrs, containers = set(), set()
        for node in ast.walk(init):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if _is_lock_ctor(node.value):
                        lock_attrs.add(attr)
                    elif _is_container_init(node.value):
                        containers.add(attr)
        if not lock_attrs or not containers:
            return

        # attr -> guarded / unguarded accesses + whether it is ever mutated
        # after __init__ (a container only ever read once construction is
        # done is immutable-in-practice and safe without the lock).
        by_attr = {}
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name == "__init__":
                continue  # construction happens-before sharing
            col = _AccessCollector(containers, lock_attrs)
            for s in stmt.body:
                col.visit(s)
            for attr, lineno, what, guarded, mutating in col.accesses:
                rec = by_attr.setdefault(attr, {"g": [], "u": [], "mut": False})
                rec["g" if guarded else "u"].append((lineno, what, stmt.name))
                rec["mut"] = rec["mut"] or mutating

        for attr in sorted(by_attr):
            rec = by_attr[attr]
            if not rec["g"] or not rec["u"] or not rec["mut"]:
                continue
            locks = "/".join(sorted(lock_attrs))
            for lineno, what, meth in sorted(rec["u"]):
                yield Finding(
                    ctx.path, lineno, self.name,
                    f"{cls.name}.{meth}: {what} on self.{attr} outside "
                    f"`with self.{locks}` — the same container is "
                    "lock-guarded elsewhere in the class, so this access "
                    "races a concurrent resize")
