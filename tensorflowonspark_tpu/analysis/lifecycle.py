"""graftcheck lifecycle: interprocedural typestate analysis for the
resources declared in ``analysis/resources.py``.

Every spec'd resource is tracked through an abstract state machine —

    ALLOCATED --release--> RELEASED     (again: double-free)
    SHARED    --release--> free-while-shared (un-share via the rc map first)
    DONATED   --read-----> use-after-donate
    RELEASED  --use------> use-after-free

— per function, flow-sensitively, reporting only DEFINITE bad states
(branches that disagree stop tracking), which is what keeps the repo
scan clean on an EMPTY baseline.  The interprocedural parts ride on the
PR 7 substrate:

- ``callgraph`` resolves helper calls, so a helper that releases its
  parameter (``self._cleanup(sock)``) releases at the call site, a
  helper that RETURNS a fresh resource (``fleet.Gateway._request``
  returning a live connection) makes the caller the owner, and the
  ``models/decode.py`` ``_jitted_*`` factory idiom is chased to the
  nested ``@jax.jit(donate_argnums=...)`` def so ``self._step = decode.
  _jitted_...()`` call sites donate the right positional/keyword args.
- ``threads`` class models attribute releases to thread roles, so a
  ``device_only`` pool (KV pages) released from a non-device role is a
  wrong-thread-role release, honoring the thread-identity-pin idiom.

Leak analysis (``lifecycle-leak``): an acquire is *covered* when it
happens under a ``with``, inside a ``try`` whose handler/finally
releases it, or when a deferred release is registered on a handle
(``h._on_done = lambda: ...release...``).  An uncovered resource leaks
when (a) a statement that can raise runs while it is live and it is
later released/escapes (the exception path skips the release), (b) an
explicit ``raise`` or ``return`` leaves it live, or (c) the function
falls off the end with it live.  Calls to ``logger``/shape builtins are
assumed non-raising; generators are exempt (the frame outlives the
walk).  Ownership transfer — returning the resource, storing it into a
``self`` container, passing it to an opaque call — ends tracking.
"""
from __future__ import annotations

import ast

from tensorflowonspark_tpu.analysis import callgraph as callgraph_mod
from tensorflowonspark_tpu.analysis import threads as threads_mod
from tensorflowonspark_tpu.analysis.core import Finding, Rule, register
from tensorflowonspark_tpu.analysis.dataflow import SHAPE_FNS, call_name
from tensorflowonspark_tpu.analysis.resources import SPECS

ALLOC = "allocated"
SHARED = "shared"
RELEASED = "released"
DONATED = "donated"

_DONATED_SPEC = next(s for s in SPECS if s.name == "donated-buffer")
# prefixes/names whose calls are assumed not to raise mid-lifecycle
_NONRAISING_PREFIXES = ("logger.", "logging.", "time.", "warnings.")
_NONRAISING_NAMES = SHAPE_FNS | {"print", "sorted", "min", "max", "range",
                                 "enumerate", "zip", "tuple", "list",
                                 "dict", "set", "frozenset"}
# container methods that cannot raise (dict.pop is only safe with a
# default — handled separately); they still transfer ownership of
# tracked arguments, so they are exempt from raise bookkeeping only
_SAFE_CONTAINER_METHODS = {"get", "setdefault", "keys", "values", "items",
                           "append", "extend", "add", "discard", "clear",
                           "update", "copy"}


def _posix(path):
    return path.replace("\\", "/")


def _self_attr(node):
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _key_of(expr):
    """Abstract location for `expr`: locals and self-attributes are the
    only bindings precise enough to track."""
    if isinstance(expr, ast.Name):
        return ("local", expr.id)
    attr = _self_attr(expr)
    if attr is not None:
        return ("attr", attr)
    return None


def _key_str(key):
    return key[1] if key[0] == "local" else f"self.{key[1]}"


def _name_matches(name, pattern):
    """Dotted-suffix pattern match: `http.client.HTTPConnection` also
    matches a from-imported bare `HTTPConnection` and vice versa."""
    if name is None:
        return False
    return (name == pattern or name.endswith("." + pattern)
            or pattern.endswith("." + name))


def _op_target(call, pattern):
    """The resource expression a release/acquire op acts on, or None
    when `call` does not match `pattern` (see resources.py for the
    pattern mini-language)."""
    if pattern.startswith("@."):
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == pattern[2:]:
            return f.value
        return None
    if _name_matches(call_name(call.func), pattern):
        return call.args[0] if call.args else None
    return None


class _Res:
    """Shared (across branch copies) record for one tracked resource."""

    __slots__ = ("spec", "line", "protected", "escaped", "raising",
                 "release_line")

    def __init__(self, spec, line, protected=False):
        self.spec = spec
        self.line = line
        self.protected = protected
        self.escaped = False
        self.raising = []       # lines that can raise while it was live
        self.release_line = None


# ---------------------------------------------------------------------------
# interprocedural summaries (cached on the project callgraph)


def _release_summary(cg, fi, depth=0, seen=None):
    """{param index: spec} for parameters `fi` definitely releases —
    directly or by forwarding to a resolvable releasing helper."""
    cache = getattr(cg, "_lifecycle_rel", None)
    if cache is None:
        cache = cg._lifecycle_rel = {}
    key = id(fi.node)
    if key in cache:
        return cache[key]
    seen = seen or set()
    if key in seen or depth > 2:
        return {}
    seen.add(key)
    params = fi.params
    out = {}
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Call):
            continue
        for spec in SPECS:
            for pat in spec.release:
                tgt = _op_target(node, pat)
                if (isinstance(tgt, ast.Name) and tgt.id in params):
                    out[params.index(tgt.id)] = spec
        callee = cg.resolve_call(node.func, fi)
        if callee is not None and callee.node is not fi.node:
            sub = _release_summary(cg, callee, depth + 1, seen)
            if sub:
                off = 1 if (callee.params and callee.params[0] == "self"
                            and isinstance(node.func, ast.Attribute)) else 0
                for idx, spec in sub.items():
                    pos = idx - off
                    if 0 <= pos < len(node.args) and \
                            isinstance(node.args[pos], ast.Name) and \
                            node.args[pos].id in params:
                        out[params.index(node.args[pos].id)] = spec
    cache[key] = out
    return out


def _match_acquire(call):
    """(spec, shared) when `call` produces a fresh resource."""
    name = call_name(call.func)
    for spec in SPECS:
        for pat in spec.acquire:
            if pat.startswith("@."):
                f = call.func
                if isinstance(f, ast.Attribute) and f.attr == pat[2:]:
                    return spec, False
            elif _name_matches(name, pat):
                return spec, False
        for pat in spec.acquire_shared:
            if _name_matches(name, pat):
                return spec, True
    return None, False


def _return_summary(cg, fi, depth=0, seen=None):
    """{tuple position: spec} for resources `fi` returns to its caller
    (position 0 = a bare non-tuple return value).  Only reported when
    every resource-bearing return agrees — disagreement goes opaque."""
    cache = getattr(cg, "_lifecycle_ret", None)
    if cache is None:
        cache = cg._lifecycle_ret = {}
    key = id(fi.node)
    if key in cache:
        return cache[key]
    seen = seen or set()
    if key in seen or depth > 2:
        return {}
    seen.add(key)
    acquired = {}              # local name -> spec
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            spec, _sh = _match_acquire(node.value)
            if spec is None:
                callee = cg.resolve_call(node.value.func, fi)
                if callee is not None and callee.node is not fi.node:
                    sub = _return_summary(cg, callee, depth + 1, seen)
                    spec = sub.get(0)
            if spec is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        acquired[tgt.id] = spec
    maps = []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        m = {}
        if isinstance(node.value, ast.Name):
            if node.value.id in acquired:
                m[0] = acquired[node.value.id]
        elif isinstance(node.value, ast.Tuple):
            for i, elt in enumerate(node.value.elts):
                if isinstance(elt, ast.Name) and elt.id in acquired:
                    m[i] = acquired[elt.id]
        elif isinstance(node.value, ast.Call):
            callee = cg.resolve_call(node.value.func, fi)
            if callee is not None and callee.node is not fi.node:
                m = dict(_return_summary(cg, callee, depth + 1, seen))
        if m:
            maps.append(m)
    out = maps[0] if maps and all(m == maps[0] for m in maps) else {}
    cache[key] = out
    return out


# ---------------------------------------------------------------------------
# donation environment


def _literal_int_tuple(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _literal_str_tuple(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _donate_kwargs(call):
    """(argnums, argnames) literals from a jit(...) call, or None when
    the call carries no (statically-known) donation."""
    nums, names = None, None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = _literal_int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            names = _literal_str_tuple(kw.value)
    if nums is None and names is None:
        return None
    return nums or (), names or ()


def _jit_call_donation(call):
    """Donation kwargs when `call` IS a jit wrapping: `jax.jit(f, ...)`
    or `functools.partial(jax.jit, ...)` (decorator form)."""
    name = call_name(call.func)
    if name is not None and (name == "jit" or name.endswith(".jit")):
        return _donate_kwargs(call)
    if name is not None and name.endswith("partial") and call.args:
        inner = call_name(call.args[0])
        if inner is not None and (inner == "jit" or inner.endswith(".jit")):
            return _donate_kwargs(call)
    return None


def _resolve_donation(kwargs, fn_node):
    """(positions, kwnames, params) with argnames folded into positions
    via the jitted function's signature."""
    nums, names = kwargs
    params = tuple(a.arg for a in fn_node.args.args) if fn_node else ()
    positions = set(nums)
    for nm in names:
        if nm in params:
            positions.add(params.index(nm))
    return frozenset(positions), frozenset(names), params


def _donation_of_value(cg, scope, value):
    """Donation info for the callable produced by `value` (an Assign
    RHS): a direct `jax.jit(f, donate_*)` call, or a call resolving to
    a `_jitted_*` factory whose nested def is jit-decorated with
    donations.  None when there is no (unambiguous) donation."""
    if not isinstance(value, ast.Call) or cg is None or scope is None:
        return None
    kwargs = _jit_call_donation(value)
    if kwargs is not None:
        fn_node = None
        if value.args:
            fi = cg.resolve_call(value.args[0], scope)
            fn_node = fi.node if fi is not None else None
        return _resolve_donation(kwargs, fn_node)
    factory = cg.resolve_call(value.func, scope)
    if factory is None:
        return None
    infos = set()
    for node in ast.walk(factory.node):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call):
                kwargs = _jit_call_donation(dec)
                if kwargs is not None:
                    infos.add(_resolve_donation(kwargs, node))
    if len(infos) == 1:
        return infos.pop()
    return None            # no donation, or ambiguous nested defs


def _class_donations(ctx, cg, cls_node):
    """attr name -> donation info for `self.X = <donating callable>`
    assignments anywhere in the class; an attr bound to factories with
    DIFFERENT donation signatures (e.g. the lora/non-lora `_step`
    variants) maps to None and is skipped — precision over recall."""
    out = {}
    if cg is None:
        return out
    for node in ast.walk(cls_node):
        if not isinstance(node, ast.Assign):
            continue
        attrs = [a for a in map(_self_attr, node.targets) if a is not None]
        if not attrs or not isinstance(node.value, ast.Call):
            continue
        scope = _enclosing_scope(cg, ctx, cls_node, node)
        if scope is None:
            continue
        d = _donation_of_value(cg, scope, node.value)
        for attr in attrs:
            if attr in out:
                if out[attr] is not None and out[attr] != d:
                    out[attr] = None
            else:
                out[attr] = d
    return {a: d for a, d in out.items() if d is not None}


def _enclosing_scope(cg, ctx, cls_node, stmt):
    """FunctionInfo of the method lexically containing `stmt`."""
    for node in ast.walk(cls_node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(n is stmt for n in ast.walk(node)):
                fi = cg.function_info(node)
                if fi is not None:
                    return fi
    return None


# ---------------------------------------------------------------------------
# per-function typestate executor


class _FnAnalysis:

    def __init__(self, ctx, cg, cls_node, fn, donate_attrs, out):
        self.ctx = ctx
        self.cg = cg
        self.cls = cls_node
        self.fn = fn
        self.donate_attrs = donate_attrs
        self.out = out
        self.scope = cg.function_info(fn) if cg is not None else None
        self.local_donate = {}
        self.lock_attrs = []          # lexical stack of held self.<lock>s
        self.pin_stack = []           # lexical thread-identity pins
        self.protect_stack = []       # sets of keys released by try
                                      # handlers/finally around us
        self.device_sites = []        # (spec, line, pin) release sites
        self.reported = set()
        self._consumed = set()
        self.is_gen = any(isinstance(n, (ast.Yield, ast.YieldFrom))
                          for n in ast.walk(fn)
                          if not isinstance(n, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))
                          or n is fn)

    # -- reporting ----------------------------------------------------------

    def _emit(self, rule, line, key, msg):
        dk = (rule, line, key)
        if dk in self.reported:
            return
        self.reported.add(dk)
        self.out.append(Finding(self.ctx.path, line, rule, msg))

    # -- env primitives -----------------------------------------------------

    def _protected_now(self, key):
        return any(key in frame for frame in self.protect_stack)

    def _bind(self, env, key, state, spec, line):
        res = _Res(spec, line, protected=self._protected_now(key))
        env[key] = (state, res)
        return res

    def _escape(self, env, key, line=None):
        ent = env.pop(key, None)
        if ent is None:
            return
        state, res = ent
        res.escaped = True
        if (state in (ALLOC, SHARED) and res.raising and not res.protected
                and res.spec.leak_check):
            self._emit(
                "lifecycle-leak", res.line, key,
                f"{res.spec.name} {_key_str(key)} (acquired here) leaks "
                f"if line {res.raising[0]} raises before ownership "
                f"transfers at line {line or res.raising[-1]}; release it "
                "in an except/finally")

    def _check_read(self, env, key, line):
        ent = env.get(key)
        if ent is None:
            return
        state, res = ent
        if state == RELEASED and not res.spec.track_from_release:
            self._emit(
                "lifecycle-use-after-free", line, key,
                f"{res.spec.name} {_key_str(key)} used after its release "
                f"at line {res.release_line}")
        elif state == DONATED:
            self._emit(
                "lifecycle-use-after-donate", line, key,
                f"{_key_str(key)} read after being donated to a jitted "
                f"call at line {res.line}; the buffer is invalidated — "
                "rebind the call's result first")

    # -- call classification ------------------------------------------------

    def _do_release(self, env, spec, key, line):
        if spec.lock and spec.lock not in self.lock_attrs:
            self._emit(
                "lifecycle-lock", line, key,
                f"{spec.name} released without holding self.{spec.lock} "
                "(the free list and refcounts it guards would race)")
        if spec.device_only:
            pin = self.pin_stack[-1] if self.pin_stack else None
            self.device_sites.append((spec, line, pin))
        if key is None:
            return
        ent = env.get(key)
        if ent is None:
            if spec.track_from_release and key[0] == "local":
                res = self._bind(env, key, RELEASED, spec, line)
                res.release_line = line
            return
        state, res = ent
        if state == RELEASED:
            if not spec.release_idempotent:
                self._emit(
                    "lifecycle-double-free", line, key,
                    f"{spec.name} {_key_str(key)} released again (first "
                    f"released at line {res.release_line})")
            return
        if state == SHARED:
            self._emit(
                "lifecycle-free-shared", line, key,
                f"{spec.name} {_key_str(key)} returned to the pool while "
                f"still shared (refcounted in self.{spec.share_map}); "
                "drop the refcount mapping first or the page will be "
                "handed out twice")
        if (state in (ALLOC, SHARED) and res.raising and not res.protected
                and res.spec.leak_check):
            self._emit(
                "lifecycle-leak", res.line, key,
                f"{res.spec.name} {_key_str(key)} (acquired here) leaks "
                f"if line {res.raising[0]} raises before the release at "
                f"line {line}; move the release into a finally/except")
        res.release_line = line
        env[key] = (RELEASED, res)

    def _donation_of_callee(self, call):
        f = call.func
        attr = _self_attr(f)
        if attr is not None:
            return self.donate_attrs.get(attr)
        if isinstance(f, ast.Name):
            return self.local_donate.get(f.id)
        return None

    def _apply_donation(self, env, dinfo, call):
        positions, kwnames, params = dinfo
        donated = []
        for i, a in enumerate(call.args):
            if i in positions:
                donated.append(a)
        for kw in call.keywords:
            if kw.arg is None:
                continue            # **kwargs: names invisible, skip
            if kw.arg in kwnames or (kw.arg in params
                                     and params.index(kw.arg) in positions):
                donated.append(kw.value)
        for expr in donated:
            key = _key_of(expr)
            if key is None:
                continue
            self._check_read(env, key, expr.lineno)   # double donation
            self._bind(env, key, DONATED, _DONATED_SPEC, call.lineno)
            # the argument read itself precedes the donation: exempt it
            # (and `x = step(x)` rebinds) from this statement's read scan
            self._consumed.update(id(n) for n in ast.walk(expr))

    def _apply_call(self, env, call):
        """Apply one call's lifecycle effects; returns True when the
        call is exempt from may-raise bookkeeping."""
        name = call_name(call.func)
        # share-map transitions: self.<rc>.pop(r) / .get handled in guards
        for spec in SPECS:
            if not spec.share_map:
                continue
            if _name_matches(name, f"self.{spec.share_map}.pop") and \
                    call.args and isinstance(call.args[0], ast.Name):
                key = ("local", call.args[0].id)
                ent = env.get(key)
                if ent is not None and ent[1].spec is spec and \
                        ent[0] == SHARED:
                    env[key] = (ALLOC, ent[1])
                return True
        for spec in SPECS:
            for pat in spec.release:
                tgt = _op_target(call, pat)
                if tgt is None:
                    continue
                self._do_release(env, spec, _key_of(tgt), call.lineno)
                return True
        dinfo = self._donation_of_callee(call)
        if dinfo is not None:
            self._apply_donation(env, dinfo, call)
            return False
        if name is not None:
            if name in _NONRAISING_NAMES or \
                    any(name.startswith(p) for p in _NONRAISING_PREFIXES):
                return True
        # helper summaries: releases-param / transfers through the call
        callee = None
        if self.cg is not None and self.scope is not None:
            callee = self.cg.resolve_call(call.func, self.scope)
        if callee is not None:
            rel = _release_summary(self.cg, callee)
            if rel:
                off = 1 if (callee.params and callee.params[0] == "self"
                            and isinstance(call.func, ast.Attribute)) else 0
                for idx, spec in rel.items():
                    pos = idx - off
                    if 0 <= pos < len(call.args):
                        self._do_release(env, spec,
                                         _key_of(call.args[pos]),
                                         call.lineno)
                        self._consumed.update(
                            id(n) for n in ast.walk(call.args[pos]))
                return False
        # opaque call: any tracked value passed as an argument may be
        # stored by the callee — ownership transfers, tracking stops
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(a):
                key = _key_of(n)
                if key is not None and key in env:
                    self._check_read(env, key, n.lineno)
                    self._escape(env, key, call.lineno)
        f = call.func
        if isinstance(f, ast.Attribute) and (
                f.attr in _SAFE_CONTAINER_METHODS
                or (f.attr == "pop" and len(call.args) == 2)):
            return True
        return False

    # -- expression scan ----------------------------------------------------

    def _scan(self, env, node, force_raising=False):
        """Process every call effect and read in `node`, then record a
        may-raise point against live uncovered resources."""
        if node is None:
            return
        raising = force_raising
        consumed = self._consumed = set()
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                # a closure capturing a tracked local has unknown
                # lifetime: stop tracking what it references
                for n in ast.walk(sub):
                    if isinstance(n, ast.Name):
                        self._escape(env, ("local", n.id), sub.lineno)
                continue
            if isinstance(sub, ast.Call):
                for spec in SPECS:
                    for pat in spec.release:
                        tgt = _op_target(sub, pat)
                        if tgt is not None:
                            consumed.update(id(n) for n in ast.walk(tgt))
                exempt = self._apply_call(env, sub)
                raising = raising or not exempt
        for sub in ast.walk(node):
            if id(sub) in consumed:
                continue
            # self.<use_attr>[r]: a read THROUGH a freed handle
            if isinstance(sub, ast.Subscript) and \
                    isinstance(sub.ctx, ast.Load):
                base = _self_attr(sub.value)
                idx = sub.slice
                if base is not None and isinstance(idx, ast.Name):
                    key = ("local", idx.id)
                    ent = env.get(key)
                    if ent is not None and ent[0] == RELEASED and \
                            base in ent[1].spec.use_attrs:
                        self._emit(
                            "lifecycle-use-after-free", sub.lineno, key,
                            f"{ent[1].spec.name} {idx.id} used via "
                            f"self.{base}[{idx.id}] after its release at "
                            f"line {ent[1].release_line}")
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                self._check_read(env, ("local", sub.id), sub.lineno)
            elif isinstance(sub, ast.Attribute) and \
                    isinstance(sub.ctx, ast.Load):
                attr = _self_attr(sub)
                if attr is not None:
                    self._check_read(env, ("attr", attr), sub.lineno)
        if raising:
            for key, (state, res) in env.items():
                if (key[0] == "local" and state in (ALLOC, SHARED)
                        and not res.protected and not res.escaped
                        and res.spec.leak_check):
                    res.raising.append(node.lineno)

    # -- leak checks --------------------------------------------------------

    def _leak_sweep(self, env, line, why):
        if self.is_gen:
            return
        for key, (state, res) in list(env.items()):
            if (key[0] == "local" and state in (ALLOC, SHARED)
                    and not res.protected and not res.escaped
                    and res.spec.leak_check):
                self._emit(
                    "lifecycle-leak", res.line, key,
                    f"{res.spec.name} {_key_str(key)} (acquired here) is "
                    f"still live at the {why} on line {line} and is never "
                    "released on this path")

    # -- statement executor -------------------------------------------------

    def exec_block(self, stmts, env):
        for st in stmts:
            env, live = self.exec_stmt(st, env)
            if not live:
                return env, False
        return env, True

    def _merge(self, a, b):
        out = {}
        for k, ent in a.items():
            other = b.get(k)
            if other is not None and other[0] == ent[0] \
                    and other[1] is ent[1]:
                out[k] = ent
        return out

    def exec_stmt(self, st, env):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            for n in ast.walk(st):
                if isinstance(n, ast.Name):
                    self._escape(env, ("local", n.id), st.lineno)
            return env, True
        if isinstance(st, ast.Return):
            self._scan(env, st.value)
            if st.value is not None:
                for n in ast.walk(st.value):
                    key = _key_of(n)
                    if key is not None:
                        self._escape(env, key, st.lineno)
            self._leak_sweep(env, st.lineno, "return")
            return env, False
        if isinstance(st, ast.Raise):
            self._scan(env, st.exc)
            self._leak_sweep(env, st.lineno, "raise")
            return env, False
        if isinstance(st, (ast.Break, ast.Continue)):
            return env, False
        if isinstance(st, ast.Assign):
            return self._do_assign(st, env), True
        if isinstance(st, ast.AnnAssign):
            self._scan(env, st.value)
            if st.value is not None:
                self._bind_targets([st.target], st.value, env)
            return env, True
        if isinstance(st, ast.AugAssign):
            self._scan(env, st.value)
            self._check_read(env, _key_of(st.target) or ("local", ""),
                             st.lineno)
            return env, True
        if isinstance(st, ast.Expr):
            self._scan(env, st.value)
            return env, True
        if isinstance(st, ast.Assert):
            self._scan(env, st.test, force_raising=True)
            return env, True
        if isinstance(st, ast.Delete):
            for tgt in st.targets:
                if isinstance(tgt, ast.Name):
                    env.pop(("local", tgt.id), None)
                elif isinstance(tgt, ast.Subscript):
                    self._del_subscript(tgt, env)
            return env, True
        if isinstance(st, ast.If):
            return self._do_if(st, env)
        if isinstance(st, (ast.For, ast.AsyncFor)):
            return self._do_for(st, env)
        if isinstance(st, ast.While):
            return self._do_while(st, env)
        if isinstance(st, ast.Try):
            return self._do_try(st, env)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            return self._do_with(st, env)
        return env, True

    def _del_subscript(self, tgt, env):
        """`del self.<rc_map>[r]` un-shares r."""
        base = _self_attr(tgt.value)
        if base is None or not isinstance(tgt.slice, ast.Name):
            return
        key = ("local", tgt.slice.id)
        ent = env.get(key)
        if ent is not None and ent[0] == SHARED and \
                ent[1].spec.share_map == base:
            env[key] = (ALLOC, ent[1])

    # -- assignment ---------------------------------------------------------

    def _do_assign(self, st, env):
        # deferred-release hook: `h._on_done = lambda: ...release...`
        # transfers ownership of everything the hook closes over
        hooks = {h for spec in SPECS for h in spec.register_hooks}
        for tgt in st.targets:
            if isinstance(tgt, ast.Attribute) and tgt.attr in hooks:
                for n in ast.walk(st.value):
                    key = _key_of(n)
                    if key is not None:
                        ent = env.get(key)
                        if ent is not None:
                            ent[1].protected = True
                            self._escape(env, key, st.lineno)
                return env
        self._scan(env, st.value)
        if isinstance(st.value, ast.Call):
            d = _donation_of_value(self.cg, self.scope, st.value)
            if d is not None:
                for tgt in st.targets:
                    if isinstance(tgt, ast.Name):
                        self.local_donate[tgt.id] = d
        self._bind_targets(st.targets, st.value, env)
        return env

    def _acquire_of_value(self, value):
        """(spec, shared, summary) produced by an Assign RHS."""
        calls = []
        if isinstance(value, ast.Call):
            calls.append(value)
        elif isinstance(value, (ast.ListComp, ast.SetComp,
                                ast.GeneratorExp)):
            if isinstance(value.elt, ast.Call):
                calls.append(value.elt)
        for call in calls:
            spec, shared = _match_acquire(call)
            if spec is not None:
                return spec, shared, None
            if self.cg is not None and self.scope is not None:
                callee = self.cg.resolve_call(call.func, self.scope)
                if callee is not None and callee.node is not self.fn:
                    ret = _return_summary(self.cg, callee)
                    if ret:
                        return None, False, ret
        return None, False, None

    def _bind_targets(self, targets, value, env):
        spec, shared, summary = self._acquire_of_value(value)
        for tgt in targets:
            if isinstance(tgt, (ast.Name, ast.Attribute)):
                key = _key_of(tgt)
                if key is None:
                    continue
                env.pop(key, None)          # rebind clears DONATED too
                if spec is not None:
                    self._bind(env, key, SHARED if shared else ALLOC,
                               spec, tgt.lineno)
                elif summary is not None and 0 in summary:
                    self._bind(env, key, ALLOC, summary[0], tgt.lineno)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for i, elt in enumerate(tgt.elts):
                    key = _key_of(elt)
                    if key is None:
                        continue
                    env.pop(key, None)
                    if summary is not None and i in summary:
                        self._bind(env, key, ALLOC, summary[i],
                                   elt.lineno)
                    elif spec is not None and i == 0:
                        # `client, _ = listener.accept()` convention
                        self._bind(env, key,
                                   SHARED if shared else ALLOC,
                                   spec, elt.lineno)
            elif isinstance(tgt, ast.Subscript):
                # storing into a container escapes the stored value
                for n in ast.walk(value):
                    key = _key_of(n)
                    if key is not None:
                        self._escape(env, key, tgt.lineno)

    # -- control flow -------------------------------------------------------

    def _share_guard(self, test, env_t, env_f):
        """Refine SHARED/exclusive across `if r in self.<rc_map>:`-style
        guards (and rc.get(r, 0) == 0 comparisons)."""
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
            return
        op, left, right = test.ops[0], test.left, test.comparators[0]
        key, base, truthy_shared = None, None, None
        if isinstance(op, (ast.In, ast.NotIn)) and \
                isinstance(left, ast.Name):
            base = _self_attr(right)
            key = ("local", left.id)
            truthy_shared = isinstance(op, ast.In)
        elif isinstance(op, (ast.Eq, ast.NotEq, ast.Gt)) and \
                isinstance(left, ast.Call) and \
                isinstance(right, ast.Constant) and right.value == 0:
            name = call_name(left.func)
            if name is not None and name.endswith(".get") and left.args \
                    and isinstance(left.args[0], ast.Name) and \
                    isinstance(left.func, ast.Attribute):
                base = _self_attr(left.func.value)
                key = ("local", left.args[0].id)
                truthy_shared = not isinstance(op, ast.Eq)
        if key is None or base is None:
            return
        ent = env_t.get(key)
        if ent is None:
            # untracked (e.g. a parameter): the guard itself proves this
            # is the spec's resource — start tracking, protected so only
            # state-transition rules (not leak) apply to it
            spec = next((s for s in SPECS if s.share_map == base), None)
            if spec is None:
                return
            rt = _Res(spec, test.lineno, protected=True)
            rf = _Res(spec, test.lineno, protected=True)
            env_t[key] = ((SHARED if truthy_shared else ALLOC), rt)
            env_f[key] = ((ALLOC if truthy_shared else SHARED), rf)
            return
        if ent[1].spec.share_map != base:
            return
        res = ent[1]
        env_t[key] = ((SHARED if truthy_shared else ALLOC), res)
        if key in env_f:
            env_f[key] = ((ALLOC if truthy_shared else SHARED), res)

    def _none_guard(self, test, env_t, env_f):
        """`if r is None:` — r holds no resource in the true branch (an
        acquire that returned None acquired nothing, e.g. freeze_session
        on a session that finished before the cut); `is not None`
        mirrors into the false branch."""
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.left, ast.Name)
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None):
            return
        op = test.ops[0]
        if isinstance(op, ast.Is):
            env_t.pop(("local", test.left.id), None)
        elif isinstance(op, ast.IsNot):
            env_f.pop(("local", test.left.id), None)

    def _do_if(self, st, env):
        self._scan(env, st.test)
        env_t, env_f = dict(env), dict(env)
        self._share_guard(st.test, env_t, env_f)
        self._none_guard(st.test, env_t, env_f)
        pin = threads_mod._pinned_thread_attr(st.test)
        if pin is not None:
            self.pin_stack.append(pin)
        env_t, live_t = self.exec_block(st.body, env_t)
        if pin is not None:
            self.pin_stack.pop()
        env_f, live_f = self.exec_block(st.orelse, env_f) \
            if st.orelse else (env_f, True)
        if live_t and live_f:
            return self._merge(env_t, env_f), True
        if live_t:
            return env_t, True
        if live_f:
            return env_f, True
        return env, False

    def _clear_loop_targets(self, tgt, env):
        for n in ast.walk(tgt):
            key = _key_of(n)
            if key is not None:
                env.pop(key, None)

    def _do_for(self, st, env):
        self._scan(env, st.iter)
        body_env = dict(env)
        self._clear_loop_targets(st.target, body_env)
        env1, _live = self.exec_block(st.body, body_env)
        # second pass from the loop-carried state so donations/releases
        # at the bottom of the body meet the reads at its top
        env2 = {**env, **env1}
        self._clear_loop_targets(st.target, env2)
        env2, _live = self.exec_block(st.body, env2)
        out = self._merge(env, env1)
        if st.orelse:
            out, _ = self.exec_block(st.orelse, out)
        return out, True

    def _do_while(self, st, env):
        self._scan(env, st.test)
        env1, _live = self.exec_block(st.body, dict(env))
        env2, _live = self.exec_block(st.body, {**env, **env1})
        out = self._merge(env, env1)
        if st.orelse:
            out, _ = self.exec_block(st.orelse, out)
        return out, True

    def _protected_keys(self, stmts):
        """Keys a try's handlers/finally release (textual match)."""
        keys = set()
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                for spec in SPECS:
                    for pat in spec.release:
                        tgt = _op_target(node, pat)
                        if tgt is not None:
                            key = _key_of(tgt)
                            if key is not None:
                                keys.add(key)
        return keys

    def _do_try(self, st, env):
        cleanup = []
        for h in st.handlers:
            cleanup.extend(h.body)
        cleanup.extend(st.finalbody)
        protected = self._protected_keys(cleanup)
        for key in protected:
            ent = env.get(key)
            if ent is not None:
                ent[1].protected = True
        self.protect_stack.append(protected)
        env_b, live_b = self.exec_block(st.body, dict(env))
        if live_b and st.orelse:
            env_b, live_b = self.exec_block(st.orelse, env_b)
        self.protect_stack.pop()
        outs = [(env_b, live_b)]
        for h in st.handlers:
            henv = self._merge(env, env_b)
            henv, hlive = self.exec_block(h.body, henv)
            outs.append((henv, hlive))
        live_outs = [e for e, lv in outs if lv]
        if live_outs:
            out = live_outs[0]
            for e in live_outs[1:]:
                out = self._merge(out, e)
            live = True
        else:
            out, live = self._merge(env, env_b), False
        if st.finalbody:
            out, flive = self.exec_block(st.finalbody, out)
            live = live and flive
        return out, live

    def _do_with(self, st, env):
        acquired, locks = [], 0
        for item in st.items:
            ce = item.context_expr
            if isinstance(ce, ast.Call):
                spec, shared = _match_acquire(ce)
                if spec is not None and \
                        isinstance(item.optional_vars, ast.Name):
                    key = ("local", item.optional_vars.id)
                    res = self._bind(env, key,
                                     SHARED if shared else ALLOC,
                                     spec, ce.lineno)
                    res.protected = True      # __exit__ covers it
                    acquired.append(key)
                else:
                    self._scan(env, ce)
            else:
                attr = _self_attr(ce)
                if attr is None and isinstance(ce, ast.Name):
                    attr = ce.id
                if attr is not None:
                    self.lock_attrs.append(attr)
                    locks += 1
        env, live = self.exec_block(st.body, env)
        for _ in range(locks):
            self.lock_attrs.pop()
        for key in acquired:
            ent = env.get(key)
            if ent is not None:
                ent[1].release_line = st.body[-1].lineno
                env[key] = (RELEASED, ent[1])
        return env, live

    # -- thread-role attribution --------------------------------------------

    def _check_roles(self):
        if not self.device_sites or self.cls is None:
            return
        model = threads_mod.class_model(self.ctx, self.cls)
        if model is None:
            return
        if not any(r.device for r in model.roles.values()):
            return
        facts = model.facts.get(self.fn.name)
        if facts is None or facts.node is not self.fn:
            return          # nested def / not a direct method: skip
        for spec, line, pin in self.device_sites:
            if pin is not None:
                rname = threads_mod._role_of_pin(model, pin)
                role = model.roles.get(rname) if rname else None
                bad = [rname] if (role is not None
                                  and not role.device) else []
            else:
                bad = sorted(
                    rn for rn, role in model.roles.items()
                    if self.fn.name in role.methods and not role.device)
            if bad:
                self._emit(
                    "lifecycle-lock", line, (spec.name, self.fn.name),
                    f"{spec.name} released in {self.fn.name}() which is "
                    f"reachable from non-device role(s) "
                    f"{'/'.join(bad)}; the {spec.name} pool is owned by "
                    "the device dispatch thread (no lock protects it)")

    # -- entry --------------------------------------------------------------

    def run(self):
        env, live = self.exec_block(self.fn.body, {})
        if live and self.fn.body:
            self._leak_sweep(env, self.fn.body[-1].lineno,
                             "end of the function")
        self._check_roles()


# ---------------------------------------------------------------------------
# file driver + registered rules


def _iter_defs(tree):
    def rec(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls, child
                yield from rec(child, cls)
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, child)
            else:
                yield from rec(child, cls)
    yield from rec(tree, None)


def _file_findings(ctx):
    cached = getattr(ctx, "_lifecycle_findings", None)
    if cached is not None:
        return cached
    out = []
    if ctx.tree is not None:
        cg = None
        if ctx.project is not None:
            cg = callgraph_mod.for_project(ctx.project)
        donations = {}
        for cls_node, fn in _iter_defs(ctx.tree):
            dmap = {}
            if cls_node is not None and cg is not None:
                if id(cls_node) not in donations:
                    donations[id(cls_node)] = _class_donations(
                        ctx, cg, cls_node)
                dmap = donations[id(cls_node)]
            _FnAnalysis(ctx, cg, cls_node, fn, dmap, out).run()
        out.sort(key=lambda f: (f.line, f.rule))
    ctx._lifecycle_findings = out
    return out


class _LifecycleRule(Rule):
    """All six rules share one cached typestate pass per file."""

    def check(self, ctx):
        for f in _file_findings(ctx):
            if f.rule == self.name:
                yield f


@register
class DoubleFreeRule(_LifecycleRule):
    name = "lifecycle-double-free"
    description = ("a resource (KV page, slot row, adapter index) is "
                   "released twice on one path")


@register
class UseAfterFreeRule(_LifecycleRule):
    name = "lifecycle-use-after-free"
    description = ("a released resource is used again (closed socket "
                   "I/O, slot-table read through a retired row)")


@register
class UseAfterDonateRule(_LifecycleRule):
    name = "lifecycle-use-after-donate"
    description = ("a buffer donated to a jitted call (donate_argnums/"
                   "argnames, including the _jitted_* factory idiom) is "
                   "read before being rebound")


@register
class LeakRule(_LifecycleRule):
    name = "lifecycle-leak"
    description = ("an acquired resource is not covered by with/finally/"
                   "a registered release hook on an exception or exit "
                   "path")


@register
class FreeWhileSharedRule(_LifecycleRule):
    name = "lifecycle-free-shared"
    description = ("a refcounted prefix-cache page is returned to the "
                   "free pool while the rc map still tracks it as "
                   "shared")


@register
class WrongLockRule(_LifecycleRule):
    name = "lifecycle-lock"
    description = ("a resource is released without the lock its spec "
                   "requires, or from a thread role that does not own "
                   "the pool")
