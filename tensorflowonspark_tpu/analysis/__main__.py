"""``python -m tensorflowonspark_tpu.analysis`` — graftcheck CLI."""
import sys

from .core import main

if __name__ == "__main__":
    sys.exit(main())
