"""graftcheck core: rule registry, file walker, suppressions, baseline, CLI.

Stdlib-only (``ast`` + ``argparse`` + ``json``) so the semantic lint tier
runs in environments with no package index — the same constraint that made
``scripts/lint.py`` a from-scratch style linter instead of pycodestyle.
This module owns everything rule-agnostic:

- the ``Rule`` registry (``@register``) that style and semantic analyzers
  plug into,
- one shared walker that reads + parses every file exactly once and hands
  each rule a ``FileContext``,
- a ``Project`` view for cross-file facts (mesh axes declared in
  ``parallel/mesh.py``, the repo-wide set of Pallas kernel entry points),
- suppression comments (``# graftcheck: disable=RULE[,RULE...]`` on the
  offending line, ``disable-next-line`` on the line above, or
  ``disable-file`` anywhere in the file; style rules also honor the legacy
  ``# noqa``),
- a baseline file of grandfathered finding fingerprints (new findings fail,
  fixed findings are reported as stale so the baseline only shrinks),
- text/JSON reporters and the argparse ``main`` used by both
  ``scripts/graftcheck.py`` and ``python -m tensorflowonspark_tpu.analysis``.
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
import time

# Paths scanned when the CLI is invoked with no arguments (mirrors the old
# scripts/lint.py default surface).  Semantic rules additionally restrict
# themselves to the package — test/example files build ad-hoc meshes and
# deliberately-broken fixtures that would drown the signal.
DEFAULT_PATHS = [
    "tensorflowonspark_tpu", "tests", "examples", "scripts",
    "bench.py", "__graft_entry__.py",
]
DEFAULT_BASELINE = os.path.join("scripts", "graftcheck_baseline.json")

PACKAGE_DIR = "tensorflowonspark_tpu"

_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*(disable(?:-next-line|-file)?)\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def fingerprint(self, lines):
        """Stable identity for the baseline: path + rule + the stripped
        source line, so findings survive unrelated line-number drift."""
        text = ""
        if 1 <= self.line <= len(lines):
            text = lines[self.line - 1].strip()
        return f"{_posix(self.path)}::{self.rule}::{text}"

    def as_dict(self):
        return {"path": _posix(self.path), "line": self.line,
                "rule": self.rule, "message": self.message}


def _posix(path):
    return path.replace(os.sep, "/")


class Rule:
    """One named check.  Subclasses set ``name``/``description`` and yield
    ``Finding``s from ``check(ctx)``.  ``scope`` is ``"all"`` (every scanned
    file) or ``"package"`` (only files under ``tensorflowonspark_tpu/``);
    ``kind`` is ``"style"`` or ``"semantic"`` (style rules honor ``# noqa``
    and are what ``scripts/lint.py`` runs)."""

    name = ""
    description = ""
    scope = "package"
    kind = "semantic"

    def applies(self, ctx):
        if self.scope == "all":
            return True
        parts = _posix(ctx.path).split("/")
        return PACKAGE_DIR in parts or ctx.path in ("bench.py", "__graft_entry__.py")

    def check(self, ctx):  # pragma: no cover - abstract
        raise NotImplementedError


REGISTRY = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    rule = cls()
    if not rule.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    REGISTRY[rule.name] = rule
    return cls


@dataclasses.dataclass
class FileContext:
    path: str
    src: str
    lines: list
    tree: object          # ast.Module, or None when the file failed to parse
    project: object = None
    # line -> set of rule names disabled on that line ("all" disables all)
    suppressions: dict = dataclasses.field(default_factory=dict)
    file_suppressions: set = dataclasses.field(default_factory=set)
    noqa_lines: set = dataclasses.field(default_factory=set)

    @classmethod
    def from_source(cls, src, path="<string>", project=None):
        lines = src.splitlines()
        try:
            tree = ast.parse(src)
            err = None
        except SyntaxError as e:
            tree, err = None, e
        ctx = cls(path=path, src=src, lines=lines, tree=tree, project=project)
        ctx.syntax_error = err
        ctx._scan_suppressions()
        return ctx

    def _scan_suppressions(self):
        for i, ln in enumerate(self.lines, start=1):
            if "# noqa" in ln:
                self.noqa_lines.add(i)
            m = _SUPPRESS_RE.search(ln)
            if not m:
                continue
            mode, rules = m.group(1), {r.strip() for r in m.group(2).split(",")}
            if mode == "disable":
                self.suppressions.setdefault(i, set()).update(rules)
            elif mode == "disable-next-line":
                self.suppressions.setdefault(i + 1, set()).update(rules)
            else:  # disable-file
                self.file_suppressions.update(rules)

    def suppressed(self, finding, rule):
        dis = self.suppressions.get(finding.line, ())
        if finding.rule in dis or "all" in dis:
            return True
        if finding.rule in self.file_suppressions or "all" in self.file_suppressions:
            return True
        if rule is not None and rule.kind == "style" and finding.line in self.noqa_lines:
            return True
        return False


class Project:
    """Cross-file facts shared by the semantic rules.

    ``mesh_axes`` — the physical mesh axis names.  Parsed lazily from the
    scanned file ending in ``parallel/mesh.py`` (module-level ``AXIS_* =
    "name"`` constants), falling back to that path on disk relative to the
    scan root; tests inject a set directly.

    ``pallas_entries`` — every top-level function name defined in a scanned
    module whose source contains a ``pallas_call``.  Deliberately coarse:
    a sharded-jit wrapper anywhere in the repo that calls one of these by
    name reaches a custom call GSPMD cannot partition.
    """

    def __init__(self, files=None, root=".", mesh_axes=None):
        self.files = files if files is not None else []
        self.root = root
        self._mesh_axes = mesh_axes
        self._pallas_entries = None

    @property
    def mesh_axes(self):
        if self._mesh_axes is None:
            self._mesh_axes = self._find_mesh_axes()
        return self._mesh_axes

    def _find_mesh_axes(self):
        for ctx in self.files:
            if _posix(ctx.path).endswith("parallel/mesh.py") and ctx.tree is not None:
                return _parse_mesh_axes(ctx.tree)
        fallback = os.path.join(self.root, PACKAGE_DIR, "parallel", "mesh.py")
        if os.path.isfile(fallback):
            try:
                with open(fallback, encoding="utf-8") as f:
                    return _parse_mesh_axes(ast.parse(f.read()))
            except (OSError, SyntaxError):
                pass
        return set()

    @property
    def pallas_entries(self):
        if self._pallas_entries is None:
            names = set()
            for ctx in self.files:
                if ctx.tree is None or "pallas_call" not in ctx.src:
                    continue
                if not _module_has_pallas_call(ctx.tree):
                    continue
                for node in ctx.tree.body:
                    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        names.add(node.name)
            self._pallas_entries = names
        return self._pallas_entries


def _parse_mesh_axes(tree):
    axes = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Name) and tgt.id.startswith("AXIS_")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    axes.add(node.value.value)
    return axes


def _module_has_pallas_call(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Name) and fn.id == "pallas_call") or \
               (isinstance(fn, ast.Attribute) and fn.attr == "pallas_call"):
                return True
    return False


# ---------------------------------------------------------------------------
# walker


def iter_py(paths, *, missing="error"):
    """Yield .py files under ``paths``.  An explicitly named path that does
    not exist raises ``FileNotFoundError`` (``missing="error"``) instead of
    being silently skipped — the old lint.py walked past typos and reported
    a clean run."""
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in {"__pycache__", ".git", ".tox",
                                              "build", "dist"})
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif missing == "error":
            raise FileNotFoundError(f"no such file or directory: {p}")


def load_project(paths, root="."):
    project = Project(root=root)
    for path in iter_py(paths):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError as e:
            raise FileNotFoundError(f"cannot read {path}: {e}") from e
        project.files.append(FileContext.from_source(src, path=path,
                                                     project=project))
    return project


def run_rules(project, rules, stats=None):
    """Run ``rules`` over every file in ``project``; returns the unsuppressed
    findings sorted by (path, line, rule).  When ``stats`` is a dict it is
    filled with ``rule name -> [seconds, finding count]`` accumulated across
    files (rule families sharing a cached per-file pass charge the shared
    work to whichever member runs first)."""
    findings = []
    for ctx in project.files:
        if ctx.tree is None:
            e = ctx.syntax_error
            f = Finding(ctx.path, e.lineno or 1, "syntax-error",
                        f"syntax error: {e.msg}")
            findings.append(f)
            continue
        for rule in rules:
            if not rule.applies(ctx):
                continue
            t0 = time.perf_counter() if stats is not None else 0.0
            n = 0
            for f in rule.check(ctx):
                if not ctx.suppressed(f, rule):
                    findings.append(f)
                    n += 1
            if stats is not None:
                entry = stats.setdefault(rule.name, [0.0, 0])
                entry[0] += time.perf_counter() - t0
                entry[1] += n
    findings.sort(key=lambda f: (_posix(f.path), f.line, f.rule))
    return findings


def analyze_source(src, path="mod.py", rules=None, mesh_axes=None):
    """Test/embedding helper: run rules over one in-memory source string."""
    project = Project(mesh_axes=mesh_axes)
    ctx = FileContext.from_source(src, path=path, project=project)
    project.files.append(ctx)
    if rules is None:
        selected = [r for r in REGISTRY.values()]
    else:
        selected = [REGISTRY[name] for name in rules]
    return run_rules(project, selected)


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path):
    if not path or not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts = {}
    for fp in data.get("findings", []):
        counts[fp] = counts.get(fp, 0) + 1
    return counts


def save_baseline(path, findings, line_map):
    fps = sorted(f.fingerprint(line_map.get(f.path, [])) for f in findings)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": fps}, f, indent=2)
        f.write("\n")


def apply_baseline(findings, baseline, line_map):
    """Split findings into (new, grandfathered) against baseline counts and
    return the stale baseline fingerprints (fixed findings the baseline
    still lists — the only allowed baseline edit is deleting those)."""
    remaining = dict(baseline)
    new, old = [], []
    for f in findings:
        fp = f.fingerprint(line_map.get(f.path, []))
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = sorted(fp for fp, n in remaining.items() if n > 0)
    return new, old, stale


# ---------------------------------------------------------------------------
# CLI


def _select_rules(select, skip, style_only):
    rules = list(REGISTRY.values())
    if style_only:
        rules = [r for r in rules if r.kind == "style"]
    if select:
        wanted = {s.strip() for s in select.split(",") if s.strip()}
        unknown = wanted - set(REGISTRY)
        if unknown:
            raise SystemExit(f"graftcheck: unknown rule(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.name in wanted]
    if skip:
        dropped = {s.strip() for s in skip.split(",") if s.strip()}
        rules = [r for r in rules if r.name not in dropped]
    return rules


def sarif_report(findings, rules=None):
    """SARIF 2.1.0 document for `findings` (CI annotates these per line;
    GitHub/VS Code both ingest this shape natively)."""
    rules = rules if rules is not None else list(REGISTRY.values())
    seen_rules = {f.rule for f in findings}
    rule_objs = [{
        "id": r.name,
        "shortDescription": {"text": r.description or r.name},
        # each rule is documented under a `.. _rule-<name>:` anchor in
        # the analysis guide; tests/test_analysis.py asserts the link
        # resolves for every registered rule
        "helpUri": f"docs/source/analysis.rst#rule-{r.name}",
        "properties": {"kind": r.kind, "scope": r.scope},
    } for r in sorted(rules, key=lambda r: r.name)
        if r.name in seen_rules or not findings]
    results = [{
        "ruleId": f.rule,
        "level": "warning",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": _posix(f.path),
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": f.line},
            },
        }],
    } for f in findings]
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftcheck",
                "informationUri":
                    "docs/source/analysis.rst",
                "rules": rule_objs,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///./"}},
            "results": results,
        }],
    }


def changed_files(root=".", base=None):
    """Posix-relative paths with uncommitted changes (worktree + index)
    plus untracked files, or None when git is unavailable / not a repo.
    With ``base``, also includes files changed between the merge-base of
    ``base`` and HEAD (what a PR diff shows)."""
    import subprocess
    out = set()
    cmds = [["git", "diff", "--name-only", "HEAD"],
            ["git", "ls-files", "--others", "--exclude-standard"]]
    if base:
        cmds.append(["git", "diff", "--name-only", f"{base}...HEAD"])
    for cmd in cmds:
        try:
            res = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, check=True)
        except (OSError, subprocess.CalledProcessError):
            return None
        out.update(ln.strip() for ln in res.stdout.splitlines()
                   if ln.strip())
    return out


def print_stats(stats, file=None):
    """Per-rule wall-time/finding-count table (sorted slowest first) —
    makes the <10 s repo-scan budget attributable per analyzer."""
    file = file or sys.stdout
    total_s = sum(s for s, _ in stats.values())
    total_n = sum(n for _, n in stats.values())
    print("graftcheck rule stats", file=file)
    print(f"{'rule':30s} {'time':>9s} {'findings':>9s}", file=file)
    for name, (secs, n) in sorted(stats.items(),
                                  key=lambda kv: -kv[1][0]):
        print(f"{name:30s} {secs * 1000.0:7.1f}ms {n:9d}", file=file)
    print(f"{'total':30s} {total_s * 1000.0:7.1f}ms {total_n:9d}",
          file=file)


def main(argv=None):
    # Importing the rule modules populates REGISTRY; done here so embedding
    # code can import core without pulling every analyzer.
    from tensorflowonspark_tpu.analysis import (  # noqa
        hostsync, lifecycle, locks, pallas_tiles, recompile, shardlint,
        style, threads, tracer, wireproto)

    ap = argparse.ArgumentParser(
        prog="graftcheck",
        description="JAX/TPU-aware stdlib static analysis (tracer hazards, "
                    "sharding lint, Pallas tile checks, lock discipline, "
                    "thread-role race analysis, jit-recompile lint, "
                    "hot-path host-sync checks, style).")
    ap.add_argument("paths", nargs="*", help="files or directories "
                    f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON (same as --format json)")
    ap.add_argument("--format", default=None, dest="fmt",
                    choices=("text", "json", "sarif", "protocol"),
                    help="report format on stdout (default text); "
                    "'protocol' dumps the extracted wire contract "
                    "(endpoints, client emissions, message planes, "
                    "propagated fields) as JSON instead of findings")
    ap.add_argument("--sarif-output", default=None, metavar="FILE",
                    help="additionally write a SARIF 2.1.0 report to FILE "
                    "(whatever --format is; CI annotation side channel)")
    ap.add_argument("--output", default=None, metavar="FILE",
                    help="with --format protocol: write the contract dump "
                    "to FILE instead of stdout (tox commands cannot "
                    "shell-redirect)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only for files git sees as "
                    "changed/untracked (full project still loads, so "
                    "cross-file rules keep their context)")
    ap.add_argument("--changed-base", default=None, metavar="REF",
                    help="with --changed-only: also treat files changed "
                    "since merge-base(REF, HEAD) as changed (PR diffs; "
                    "e.g. --changed-base origin/main)")
    ap.add_argument("--stats", action="store_true",
                    help="print a per-rule wall-time and finding-count "
                    "table after the report (rule families sharing one "
                    "cached pass charge it to the member that runs first)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} if present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline with the current findings "
                    "(shrink-only: refuses to ADD fingerprints unless "
                    "--grow-baseline is also given)")
    ap.add_argument("--grow-baseline", action="store_true",
                    help="with --update-baseline: allow the baseline to "
                    "gain fingerprints (bootstrap/grandfathering only)")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule names to run")
    ap.add_argument("--skip", default=None, metavar="RULES",
                    help="comma-separated rule names to skip")
    ap.add_argument("--style-only", action="store_true",
                    help="run only the style tier (what scripts/lint.py runs)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--strict", action="store_true",
                    help="accepted for scripts/lint.py compatibility (no-op)")
    args = ap.parse_args(argv)
    fmt = args.fmt or ("json" if args.as_json else "text")

    if args.list_rules:
        for name in sorted(REGISTRY):
            r = REGISTRY[name]
            print(f"{name:28s} [{r.kind}/{r.scope}] {r.description}")
        return 0

    rules = _select_rules(args.select, args.skip, args.style_only)

    paths = args.paths or [p for p in DEFAULT_PATHS if os.path.exists(p)]
    try:
        project = load_project(paths)
    except FileNotFoundError as e:
        print(f"graftcheck: error: {e}", file=sys.stderr)
        return 2

    if fmt == "protocol":
        from tensorflowonspark_tpu.analysis import wireproto as _wp
        doc = json.dumps(_wp.protocol_dump(project), indent=2)
        if args.output:
            os.makedirs(os.path.dirname(args.output) or ".", exist_ok=True)
            with open(args.output, "w", encoding="utf-8") as f:
                f.write(doc + "\n")
            print(f"graftcheck: wire-protocol dump -> {args.output}")
        else:
            print(doc)
        return 0

    stats = {} if args.stats else None
    findings = run_rules(project, rules, stats=stats)
    line_map = {ctx.path: ctx.lines for ctx in project.files}

    if args.changed_only:
        changed = changed_files(base=args.changed_base)
        if changed is None:
            print("graftcheck: error: --changed-only needs a git checkout",
                  file=sys.stderr)
            return 2
        findings = [f for f in findings if _posix(f.path) in changed]

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        baseline_path = DEFAULT_BASELINE if os.path.isfile(DEFAULT_BASELINE) else None
    if args.no_baseline:
        baseline_path = None

    if args.update_baseline:
        target = args.baseline or DEFAULT_BASELINE
        # shrink-only contract: grandfathering NEW findings into the
        # baseline is a reviewed, explicit act (--grow-baseline), never a
        # side effect of refreshing it
        current = load_baseline(target)
        added = []
        pool = dict(current)
        for f in findings:
            fp = f.fingerprint(line_map.get(f.path, []))
            if pool.get(fp, 0) > 0:
                pool[fp] -= 1
            else:
                added.append(fp)
        if added and not args.grow_baseline:
            print(f"graftcheck: error: refusing to ADD {len(added)} "
                  f"fingerprint(s) to {target} (shrink-only baseline; "
                  "fix the findings or pass --grow-baseline):",
                  file=sys.stderr)
            for fp in sorted(added):
                print(f"  {fp}", file=sys.stderr)
            return 2
        save_baseline(target, findings, line_map)
        print(f"graftcheck: wrote {len(findings)} finding(s) to {target}")
        return 0

    baseline = load_baseline(baseline_path)
    new, old, stale = apply_baseline(findings, baseline, line_map)

    if args.sarif_output:
        sarif_dir = os.path.dirname(args.sarif_output)
        if sarif_dir:
            os.makedirs(sarif_dir, exist_ok=True)
        with open(args.sarif_output, "w", encoding="utf-8") as fh:
            json.dump(sarif_report(new, rules), fh, indent=2)
            fh.write("\n")

    if fmt == "sarif":
        print(json.dumps(sarif_report(new, rules), indent=2))
    elif fmt == "json":
        print(json.dumps({
            "findings": [f.as_dict() for f in new],
            "baselined": [f.as_dict() for f in old],
            "stale_baseline": stale,
        }, indent=2))
    else:
        for f in new:
            print(f"{_posix(f.path)}:{f.line}: [{f.rule}] {f.message}")
        if stale:
            print(f"graftcheck: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (finding fixed — "
                  "delete from the baseline):")
            for fp in stale:
                print(f"  {fp}")
        if new:
            n_files = len({f.path for f in new})
            print(f"graftcheck: {len(new)} finding(s) in {n_files} file(s)"
                  + (f" ({len(old)} baselined)" if old else ""))
        else:
            print("graftcheck clean"
                  + (f" ({len(old)} baselined finding(s))" if old else ""))
    if stats is not None:
        print_stats(stats)
    return 1 if new else 0
