"""graftcheck wireproto: whole-fleet wire-protocol contract analysis.

The fleet's protocol is implicit: server routes are ``if path == ...``
chains in ``BaseHTTPRequestHandler`` subclasses, clients build paths
with f-strings three modules away, the rendezvous/KV planes dispatch on
``msg["type"]`` / ``req["kind"]`` string compares, and contract fields
must be hand-copied into every payload that crosses a process boundary.
This pass extracts both sides of that contract from the AST (on the
PR 7 callgraph substrate) and cross-checks them:

- **server route table** — every ``do_GET``/``do_POST`` method of a
  handler class, its path predicates (literal compares, membership
  tuples, ``startswith`` prefixes, f-string ``:verb`` compares — also
  when assigned to ``is_predict``-style locals), and the status codes
  each route can ``send_response()``, summarized through ``self._send``
  -style helpers;
- **client emission sites** — every ``conn.request(method, path, ...)``
  plus the wrapper closure over it (``Gateway._request``,
  ``FleetClient._call``, ``probe``): a wrapper forwarding its
  ``method``/``path`` params becomes an emitter, so the call site that
  pins the literals is where the emission is recorded, with the
  headers/body fields written along the chain and the status codes the
  chain's ``resp.status`` checks distinguish;
- **message planes** — for the modules in ``protocol.MESSAGE_PLANES``,
  the dispatch cases (compares against the plane key on received
  dicts) versus the emitted frames (``{"type": ...}`` literals passed
  to a send, including via a local variable);
- **propagated contract fields** — each ``protocol.FIELD_SPECS`` row
  is verified by walking its carrier functions (and their resolvable
  callees) for a write of the field.

Rules: ``wire-unhandled-endpoint`` (client emits what no handler
routes), ``wire-dead-endpoint`` (route or dispatch case no client
emits, minus the declared operator-only surfaces),
``wire-dropped-field`` (a spec carrier stopped writing a contract
field), ``wire-status-unhandled`` (a retry-driven emission whose
status handling cannot tell a permanent 4xx from a transient failure,
against a route that really emits one).  ``protocol_dump`` backs the
CLI's ``--format protocol`` JSON contract dump.

Like every graftcheck pass: stdlib ``ast`` only, best-effort
resolution — a dynamic path (``self.path`` relays) is recorded but
exempt from matching, so missed edges cost recall, never precision.
"""
from __future__ import annotations

import ast
import dataclasses
import re

from . import callgraph as callgraph_mod
from .core import Finding, Rule, register, _posix
from .protocol import (ACK_MESSAGES, EXTERNAL_ENDPOINTS, FIELD_SPECS,
                       MESSAGE_PLANES, ClientCall, Endpoint, MessageCase)

HTTP_METHODS = ("GET", "POST", "PUT", "DELETE", "HEAD", "PATCH")

# 4xx a retry policy may legitimately treat like a transient failure
RETRYABLE_4XX = (408, 429)

_HEADER_RE = re.compile(r"^[A-Z][A-Za-z0-9]*(?:-[A-Za-z0-9]+)+$")
_PCT_RE = re.compile(r"%[srdif]")
_BODYISH = ("body", "payload", "req", "meta", "msg", "record")


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _norm(pattern):
    """Canonical path pattern: query string stripped, duplicate
    wildcards collapsed, trailing slash dropped (handlers rstrip)."""
    pattern = pattern.split("?")[0]
    while "**" in pattern:
        pattern = pattern.replace("**", "*")
    if len(pattern) > 1 and pattern.endswith("/"):
        pattern = pattern.rstrip("/") or "/"
    return pattern


def _pattern_exprs(node, fn_node=None, _depth=0):
    """Every path pattern ``node`` can evaluate to (dynamic pieces as
    ``*``); ``[]`` when the expression is not statically path-shaped.

    Handles constants, f-strings, ``+`` concatenation, ``%`` formatting,
    conditional expressions, and (when ``fn_node`` is given) local names
    resolved through their assignments in the enclosing function — the
    ``path = f"...:resume"`` / ``path = f"...:generate"`` idiom.
    """
    s = _const_str(node)
    if s is not None:
        return [s]
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return ["".join(parts)]
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        lefts = _pattern_exprs(node.left, fn_node, _depth)
        rights = _pattern_exprs(node.right, fn_node, _depth)
        if lefts and rights:
            return [a + b for a in lefts for b in rights]
        return []
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        left = _const_str(node.left)
        if left is not None:
            return [_PCT_RE.sub("*", left)]
        return []
    if isinstance(node, ast.IfExp):
        a = _pattern_exprs(node.body, fn_node, _depth)
        b = _pattern_exprs(node.orelse, fn_node, _depth)
        return a + b if a and b else []
    if isinstance(node, ast.Name) and fn_node is not None and _depth < 3:
        out = []
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and n.targets[0].id == node.id and n.value is not node:
                got = _pattern_exprs(n.value, fn_node, _depth + 1)
                if not got:
                    return []      # one dynamic rebind poisons the name
                out.extend(got)
        return out
    return []


# ---------------------------------------------------------------------------
# server route table


def _is_pathish(expr):
    """Does this expression read the request path?  Matches ``path``
    locals, ``self.path``, and anything chained off them."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and "path" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "path" in n.attr.lower():
            return True
    return False


def _endswith_const(expr):
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "endswith" and expr.args \
            and _is_pathish(expr.func.value):
        return _const_str(expr.args[0])
    return None


def _route_tests(test, fn_node):
    """``[(pattern, kind)]`` for every route predicate in a boolean
    expression (Or unions, And combines startswith+endswith)."""
    out = []
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        for v in test.values:
            out.extend(_route_tests(v, fn_node))
        return out
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        prefixes, suffix, others = [], None, []
        for v in test.values:
            for pat, kind in _route_tests(v, fn_node):
                (prefixes if kind == "prefix" else others).append((pat, kind))
            suffix = suffix or _endswith_const(v)
        if prefixes and suffix:
            pat = prefixes[0][0]
            return [(pat + suffix if pat.endswith("*") else pat, "verb")]
        return prefixes or others
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        op = test.ops[0]
        left, right = test.left, test.comparators[0]
        if isinstance(op, ast.Eq):
            for a, b in ((left, right), (right, left)):
                if _is_pathish(a):
                    pats = _pattern_exprs(b, fn_node)
                    return [(p, "verb" if "*" in p else "exact")
                            for p in pats]
        if isinstance(op, ast.In) and _is_pathish(left) \
                and isinstance(right, (ast.Tuple, ast.List, ast.Set)):
            return [(p, "exact") for elt in right.elts
                    for p in _pattern_exprs(elt, fn_node)]
    if isinstance(test, ast.Call) and isinstance(test.func, ast.Attribute) \
            and test.func.attr == "startswith" and test.args \
            and _is_pathish(test.func.value):
        return [(p + "*", "prefix")
                for p in _pattern_exprs(test.args[0], fn_node)]
    return []


def _status_summary(cg, fi, memo, _active=None):
    """``(codes, param_idxs)``: literal status codes ``fi`` can pass to
    ``send_response`` (directly or through helpers like ``_send``), and
    the indices of its own params that flow into one."""
    key = id(fi.node)
    if key in memo:
        return memo[key]
    _active = _active if _active is not None else set()
    if key in _active:
        return set(), set()
    _active.add(key)
    codes, params = set(), set()
    for call in ast.walk(fi.node):
        if not isinstance(call, ast.Call):
            continue
        for c, p in _codes_for_call(call, cg, fi, memo, _active):
            if p is not None:
                params.add(p)
            else:
                codes.add(c)
    _active.discard(key)
    memo[key] = (codes, params)
    return memo[key]


def _code_values(expr, fi):
    """Status values of a code argument: ``[(code, None)]`` for
    literals / dynamic ``"*"``, ``[(None, idx)]`` for a forwarded
    param of ``fi``."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return [(int(expr.value), None)]
    if isinstance(expr, ast.IfExp):
        return _code_values(expr.body, fi) + _code_values(expr.orelse, fi)
    if isinstance(expr, ast.Name) and fi is not None and expr.id in fi.params:
        return [(None, fi.params.index(expr.id))]
    return [("*", None)]


def _callee_of(call, cg, fi):
    """(FunctionInfo, arg_offset) for a call, or (None, 0).  Falls back
    to nothing here — name-fallback is emission-specific."""
    callee = cg.resolve_call(call.func, fi)
    if callee is None:
        return None, 0
    offset = 1 if (callee.cls is not None
                   and isinstance(call.func, ast.Attribute)) else 0
    return callee, offset


def _call_arg(call, idx, offset, callee):
    """The expression bound to the callee's param ``idx``."""
    pos = idx - offset
    if 0 <= pos < len(call.args):
        return call.args[pos]
    params = callee.params
    if idx < len(params):
        for kw in call.keywords:
            if kw.arg == params[idx]:
                return kw.value
    return None


def _codes_for_call(call, cg, fi, memo, _active=None):
    """Status codes one call contributes (direct send_response, or a
    helper whose summary forwards a code param)."""
    out = []
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "send_response":
        if call.args:
            out.extend(_code_values(call.args[0], fi))
        return out
    callee, offset = _callee_of(call, cg, fi)
    if callee is None or callee is fi:
        return out
    sub_codes, sub_params = _status_summary(cg, callee, memo, _active)
    for c in sub_codes:
        out.append((c, None))
    for idx in sub_params:
        arg = _call_arg(call, idx, offset, callee)
        if arg is not None:
            out.extend(_code_values(arg, fi))
    return out


def _statuses_in(stmts, cg, fi, memo):
    codes = set()
    for st in stmts:
        for call in ast.walk(st):
            if isinstance(call, ast.Call):
                for c, p in _codes_for_call(call, cg, fi, memo):
                    if p is None:
                        codes.add(c)
    return codes


def _extract_routes(cg, memo):
    """Every Endpoint in every handler class of the project."""
    endpoints = []
    for mi in cg.modules.values():
        layer = mi.modname.rsplit(".", 1)[-1]
        for ci in mi.classes.values():
            for mname, fi in sorted(ci.methods.items()):
                if not mname.startswith("do_") or len(mname) <= 3:
                    continue
                method = mname[3:].upper()
                if method not in HTTP_METHODS:
                    continue
                endpoints.extend(
                    _routes_of_handler(cg, mi, layer, fi, method, memo))
    return endpoints


def _routes_of_handler(cg, mi, layer, fi, method, memo):
    branch_routes = []       # (routes, body stmts)
    assign_routes = []       # (routes, lineno)
    attributed = set()       # stmt ids inside attributed route bodies

    def scan(stmts):
        for st in stmts:
            if isinstance(st, ast.If):
                routes = _route_tests(st.test, fi.node)
                if routes:
                    branch_routes.append((routes, st.body, st.lineno))
                    for b in st.body:
                        attributed.add(id(b))
                    scan(st.orelse)
                    continue
                scan(st.body)
                scan(st.orelse)
            elif isinstance(st, ast.Assign):
                routes = _route_tests(st.value, fi.node)
                if routes:
                    assign_routes.append((routes, st.lineno))
            elif isinstance(st, (ast.Try,)):
                scan(st.body)
                for h in st.handlers:
                    scan(h.body)
                scan(st.orelse)
                scan(st.finalbody)
            elif isinstance(st, (ast.With, ast.For, ast.While)):
                scan(st.body)
                scan(getattr(st, "orelse", []))

    scan(fi.node.body)

    # statuses emitted outside any attributed route branch: the shared
    # tail (404 fallthrough, draining 503, the predict/generate try) —
    # attached to the assignment-matched routes, which is where the
    # shared tail's work happens
    residual = set()

    def residual_scan(stmts):
        for st in stmts:
            if id(st) in attributed:
                continue
            if isinstance(st, ast.If):
                for call in ast.walk(st.test):
                    if isinstance(call, ast.Call):
                        for c, p in _codes_for_call(call, cg, fi, memo):
                            if p is None:
                                residual.add(c)
                residual_scan(st.body)
                residual_scan(st.orelse)
            elif isinstance(st, ast.Try):
                residual_scan(st.body)
                for h in st.handlers:
                    residual_scan(h.body)
                residual_scan(st.orelse)
                residual_scan(st.finalbody)
            elif isinstance(st, (ast.With, ast.For, ast.While)):
                residual_scan(st.body)
                residual_scan(getattr(st, "orelse", []))
            else:
                residual.update(_statuses_in([st], cg, fi, memo))

    residual_scan(fi.node.body)

    out = []
    for routes, body, lineno in branch_routes:
        statuses = frozenset(_statuses_in(body, cg, fi, memo))
        for pat, kind in routes:
            out.append(Endpoint(method=method, path=_norm(pat), layer=layer,
                                handler=fi.qualname, line=lineno, kind=kind,
                                statuses=tuple(sorted(statuses, key=str))))
    res = tuple(sorted(residual, key=str))
    for routes, lineno in assign_routes:
        for pat, kind in routes:
            out.append(Endpoint(method=method, path=_norm(pat), layer=layer,
                                handler=fi.qualname, line=lineno, kind=kind,
                                statuses=res))
    return out


# ---------------------------------------------------------------------------
# client emission sites


@dataclasses.dataclass
class _Emit:
    """One way a function puts bytes on the wire: each slot is
    ``("lit", value)``, ``("param", idx)``, or ``None`` (dynamic)."""
    method: object
    path: object
    site: object               # the ast.Call at this function's level
    chain: tuple               # FunctionInfo chain down to conn.request

    def key(self):
        return (self.method, self.path)


def _is_base_emit(call):
    """``X.request(method, path, ...)`` — a direct wire emission site
    (``self.request`` would be handler-side, not a client)."""
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "request" and len(call.args) >= 2
            and not (isinstance(call.func.value, ast.Name)
                     and call.func.value.id == "self"))


def _slot(expr, params, fn_node, verb):
    s = _const_str(expr)
    if verb:
        if s is not None:
            return ("lit", s.upper()) if s.upper() in HTTP_METHODS else None
        if isinstance(expr, ast.Name) and expr.id in params:
            return ("param", params.index(expr.id))
        return None
    pats = _pattern_exprs(expr, fn_node)
    if pats:
        return ("lit", tuple(_norm(p) for p in pats))
    if isinstance(expr, ast.Name) and expr.id in params:
        return ("param", params.index(expr.id))
    return None


def _emitters_fixpoint(cg):
    """Propagate emitter summaries up the wrapper chain; returns
    ``(emissions, relays, call_sites)`` where emissions are concrete
    ``_Emit``s with both slots literal, attributed to the function that
    pinned them, and ``call_sites`` maps FunctionInfo -> [(caller,
    call node)] for the retry-context scan."""
    funcs = list(cg.info_by_node.values())
    summaries = {}            # FunctionInfo -> [_Emit with a param slot]
    emissions, relays = [], []

    def classify(em):
        meth, path = em.method, em.path
        if meth is not None and meth[0] == "lit":
            if path is not None and path[0] == "lit":
                emissions.append(em)
                return
            if path is None:
                relays.append(em)
                return
        if (meth is not None and meth[0] == "param") or \
                (path is not None and path[0] == "param"):
            summaries.setdefault(em.chain[0], []).append(em)

    # One sweep over every AST: pick up the base emission sites and
    # resolve every call exactly once.  The fixpoint rounds below then
    # touch only the (few) calls aimed at summary-holding wrappers
    # instead of re-walking the whole project per round.
    call_sites = {}
    sites_seen = set()
    calls_to = {}             # FunctionInfo -> [(caller, call, offset)]
    unresolved = {}           # terminal name -> [(caller, call)]
    for caller in funcs:
        params = caller.params
        for call in ast.walk(caller.node):
            if not isinstance(call, ast.Call):
                continue
            if _is_base_emit(call):
                classify(_Emit(
                    method=_slot(call.args[0], params, caller.node,
                                 verb=True),
                    path=_slot(call.args[1], params, caller.node,
                               verb=False),
                    site=call, chain=(caller,)))
            callee, offset = _callee_of(call, cg, caller)
            if callee is None:
                # `gw._request(...)` — the receiver is a local, so the
                # callgraph punts; remember the terminal name for the
                # unique-wrapper fallback resolved per round below
                term = call.func.attr \
                    if isinstance(call.func, ast.Attribute) else \
                    (call.func.id if isinstance(call.func, ast.Name)
                     else None)
                if term is not None:
                    unresolved.setdefault(term, []).append((caller, call))
                continue
            calls_to.setdefault(callee, []).append((caller, call, offset))
            sk = (id(caller.node), id(call), id(callee.node))
            if sk not in sites_seen:
                sites_seen.add(sk)
                call_sites.setdefault(callee, []).append((caller, call))

    emit_seen = set()
    for _ in range(8):
        grown = False
        names = {}
        for fi in summaries:
            names.setdefault(fi.name, []).append(fi)
        work = []
        for callee in list(summaries):
            for caller, call, offset in calls_to.get(callee, ()):
                work.append((caller, call, callee, offset))
            for caller, call in unresolved.get(callee.name, ()):
                # fall back to a unique name match among known emitter
                # wrappers — ambiguous names stay unresolved
                if len(names.get(callee.name, ())) != 1:
                    continue
                offset = 1 if isinstance(call.func, ast.Attribute) else 0
                sk = (id(caller.node), id(call), id(callee.node))
                if sk not in sites_seen:
                    sites_seen.add(sk)
                    call_sites.setdefault(callee, []).append((caller, call))
                work.append((caller, call, callee, offset))
        for caller, call, callee, offset in work:
            for em in list(summaries.get(callee, ())):
                new = _derive(em, call, offset, callee, caller,
                              caller.params)
                if new is None:
                    continue
                if new.method and new.method[0] == "lit" and \
                        new.path and new.path[0] == "lit":
                    k = (id(caller.node), call.lineno, new.key())
                    if k not in emit_seen:
                        emit_seen.add(k)
                        emissions.append(new)
                elif new.method and new.method[0] == "lit" and \
                        new.path is None:
                    k = (id(caller.node), call.lineno, "relay")
                    if k not in emit_seen:
                        emit_seen.add(k)
                        relays.append(new)
                else:
                    have = summaries.setdefault(caller, [])
                    if all(h.key() != new.key() or
                           h.chain != new.chain for h in have):
                        have.append(new)
                        grown = True
        if not grown:
            break
    return emissions, relays, call_sites


def _derive(em, call, offset, callee, caller, caller_params):
    def rebind(slot):
        if slot is None or slot[0] == "lit":
            return slot
        arg = _call_arg(call, slot[1], offset, callee)
        if arg is None:
            return None
        return _slot(arg, caller_params, caller.node,
                     verb=(slot is em.method))
    meth = rebind(em.method)
    path = rebind(em.path)
    if meth is None and path is None:
        return None
    return _Emit(method=meth, path=path, site=call,
                 chain=(caller,) + em.chain)


def _header_keys(fn_node):
    keys = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Dict):
            for k in n.keys:
                s = _const_str(k) if k is not None else None
                if s and _HEADER_RE.match(s):
                    keys.add(s)
        elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Subscript):
            s = _const_str(n.targets[0].slice)
            if s and _HEADER_RE.match(s):
                keys.add(s)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("putheader", "setdefault") and n.args:
            s = _const_str(n.args[0])
            if s and _HEADER_RE.match(s):
                keys.add(s)
    return keys


def _payload_fields(fn_node):
    fields = set()
    for n in ast.walk(fn_node):
        tgt = None
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            tgt = n.targets[0]
            if isinstance(tgt, ast.Subscript) \
                    and isinstance(tgt.value, ast.Name) \
                    and any(tgt.value.id.startswith(b) for b in _BODYISH):
                s = _const_str(tgt.slice)
                if s:
                    fields.add(s)
            elif isinstance(tgt, ast.Name) \
                    and any(tgt.id.startswith(b) for b in _BODYISH) \
                    and isinstance(n.value, ast.Dict):
                for k in n.value.keys:
                    s = _const_str(k) if k is not None else None
                    if s:
                        fields.add(s)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "setdefault" and n.args \
                and isinstance(n.func.value, ast.Name) \
                and any(n.func.value.id.startswith(b) for b in _BODYISH):
            s = _const_str(n.args[0])
            if s:
                fields.add(s)
    return fields


def _status_checks(fn_node):
    """``(consts, has_range)``: codes this function's ``.status``
    comparisons single out, and whether any class-boundary comparison
    (``>= 500``, ``400 <= s < 500``) exists."""
    consts, has_range = set(), False

    def statusish(e):
        return (isinstance(e, ast.Attribute) and e.attr == "status") or \
               (isinstance(e, ast.Name) and e.id == "status")

    for n in ast.walk(fn_node):
        if not isinstance(n, ast.Compare):
            continue
        operands = [n.left] + list(n.comparators)
        if not any(statusish(o) for o in operands):
            continue
        for op, lhs, rhs in zip(n.ops, operands, operands[1:]):
            other = rhs if statusish(lhs) else lhs
            if isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE)):
                has_range = True
            elif isinstance(op, (ast.Eq, ast.NotEq)):
                if isinstance(other, ast.Constant) \
                        and isinstance(other.value, int):
                    consts.add(int(other.value))
            elif isinstance(op, (ast.In, ast.NotIn)):
                if isinstance(other, (ast.Tuple, ast.List, ast.Set)):
                    for elt in other.elts:
                        if isinstance(elt, ast.Constant) \
                                and isinstance(elt.value, int):
                            consts.add(int(elt.value))
    return consts, has_range


def _is_retry_loop(node):
    if isinstance(node, ast.For):
        names = {x.id.lower() for x in ast.walk(node.target)
                 if isinstance(x, ast.Name)}
        if any("attempt" in s or "retr" in s for s in names):
            return True
        it = node.iter
        if isinstance(it, ast.Call):
            f = it.func
            nm = f.attr if isinstance(f, ast.Attribute) \
                else getattr(f, "id", "")
            return nm in ("sleeps", "retries", "backoff", "attempts")
        return False
    if isinstance(node, ast.While):
        return any(isinstance(x, ast.Name)
                   and ("attempt" in x.id.lower() or "retr" in x.id.lower())
                   for x in ast.walk(node.test))
    return False


def _in_retry_loop(fn_node, target):
    for n in ast.walk(fn_node):
        if isinstance(n, (ast.For, ast.While)) and _is_retry_loop(n):
            for sub in ast.walk(n):
                if sub is target:
                    return True
    return False


def _client_calls(cg):
    emissions, relays, call_sites = _emitters_fixpoint(cg)
    out = []
    for em in emissions:
        top = em.chain[0]
        headers, fields = set(), set()
        consts, has_range = set(), False
        for fi in em.chain:
            headers |= _header_keys(fi.node)
            fields |= _payload_fields(fi.node)
            c, r = _status_checks(fi.node)
            consts |= c
            has_range = has_range or r
        retried = _in_retry_loop(top.node, em.site) or any(
            _in_retry_loop(caller.node, call)
            for caller, call in call_sites.get(top, ()))
        # distinct pattern exprs can normalize identically (e.g. a
        # querystring-only IfExp); emit each pattern once
        for pat in dict.fromkeys(em.path[1]):
            out.append(ClientCall(
                method=em.method[1], path=pat,
                layer=top.module.modname.rsplit(".", 1)[-1],
                caller=top.qualname, line=em.site.lineno,
                headers=tuple(sorted(headers)),
                body_fields=tuple(sorted(fields)),
                statuses=tuple(sorted(consts)) + (("range",)
                                                  if has_range else ()),
                retried=retried))
    relay_calls = []
    for em in relays:
        top = em.chain[0]
        relay_calls.append(ClientCall(
            method=em.method[1], path=None,
            layer=top.module.modname.rsplit(".", 1)[-1],
            caller=top.qualname, line=em.site.lineno))
    return out, relay_calls


# ---------------------------------------------------------------------------
# message planes


def _receiveish(call):
    return isinstance(call, ast.Call) \
        and isinstance(call.func, ast.Attribute) \
        and call.func.attr in ("receive", "recv", "recv_msg", "read_msg")


def _plane_vars(fi, key):
    """Names in ``fi`` that hold a received message dict or its
    dispatch key: receive() results, dispatch/serve params, and
    ``mtype = msg.get(key)`` re-bindings."""
    msg_vars = set()
    dispatchish = any(tok in fi.name.lower()
                      for tok in ("dispatch", "serve", "handle"))
    if dispatchish:
        msg_vars.update(p for p in fi.params if p != "self")
    for n in ast.walk(fi.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and _receiveish(n.value):
            msg_vars.add(n.targets[0].id)
    key_vars = set()
    for _ in range(2):
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                src = _key_read(n.value, msg_vars, key)
                if src:
                    key_vars.add(n.targets[0].id)
    return msg_vars, key_vars


def _key_read(expr, msg_vars, key):
    """Is ``expr`` a read of the plane key from a message var?"""
    if isinstance(expr, ast.Subscript) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id in msg_vars \
            and _const_str(expr.slice) == key:
        return True
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "get" and expr.args \
            and isinstance(expr.func.value, ast.Name) \
            and expr.func.value.id in msg_vars \
            and _const_str(expr.args[0]) == key:
        return True
    return False


def _handled_cases(fi, key, layer):
    msg_vars, key_vars = _plane_vars(fi, key)
    if not msg_vars and not key_vars:
        return []
    out = []
    for n in ast.walk(fi.node):
        if not isinstance(n, ast.Compare):
            continue
        operands = [n.left] + list(n.comparators)
        keyish = [o for o in operands
                  if _key_read(o, msg_vars, key)
                  or (isinstance(o, ast.Name) and o.id in key_vars)]
        if not keyish:
            continue
        for op, lhs, rhs in zip(n.ops, operands, operands[1:]):
            other = rhs if keyish[0] is lhs or lhs in keyish else lhs
            if isinstance(op, (ast.Eq, ast.NotEq)):
                s = _const_str(other)
                if s is not None:
                    out.append(MessageCase(key=key, value=s, side="handle",
                                           layer=layer, where=fi.qualname,
                                           line=n.lineno))
            elif isinstance(op, (ast.In, ast.NotIn)) \
                    and isinstance(other, (ast.Tuple, ast.List, ast.Set)):
                for elt in other.elts:
                    s = _const_str(elt)
                    if s is not None:
                        out.append(MessageCase(key=key, value=s,
                                               side="handle", layer=layer,
                                               where=fi.qualname,
                                               line=n.lineno))
    return out


_SENDISH = ("send", "_request", "request", "reply", "send_msg")


def _emitted_cases(fi, key, layer):
    out = []
    local_dicts = {}
    for n in ast.walk(fi.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and isinstance(n.value, ast.Dict):
            local_dicts[n.targets[0].id] = n.value

    def dict_case(d, line):
        for k, v in zip(d.keys, d.values):
            if k is not None and _const_str(k) == key:
                s = _const_str(v)
                if s is not None:
                    out.append(MessageCase(key=key, value=s, side="emit",
                                           layer=layer, where=fi.qualname,
                                           line=line))

    for n in ast.walk(fi.node):
        if not isinstance(n, ast.Call):
            continue
        fname = n.func.attr if isinstance(n.func, ast.Attribute) \
            else (n.func.id if isinstance(n.func, ast.Name) else None)
        if fname not in _SENDISH:
            continue
        for arg in list(n.args) + [kw.value for kw in n.keywords]:
            if isinstance(arg, ast.Dict):
                dict_case(arg, n.lineno)
            elif isinstance(arg, ast.Name) and arg.id in local_dicts:
                dict_case(local_dicts[arg.id], n.lineno)
    return out


def _message_cases(cg):
    cases = []
    for mi in cg.modules.values():
        layer = mi.modname.rsplit(".", 1)[-1]
        key = MESSAGE_PLANES.get(layer)
        if key is None:
            continue
        for fi in cg.info_by_node.values():
            if fi.module is not mi:
                continue
            cases.extend(_handled_cases(fi, key, layer))
            cases.extend(_emitted_cases(fi, key, layer))
    return cases


# ---------------------------------------------------------------------------
# propagated contract fields


def _writes_field(fi, field, cg, depth=2, _seen=None):
    _seen = _seen if _seen is not None else set()
    if id(fi.node) in _seen:
        return False
    _seen.add(id(fi.node))
    for n in ast.walk(fi.node):
        if isinstance(n, ast.Dict):
            for k in n.keys:
                if k is not None and _const_str(k) == field:
                    return True
        elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Subscript) \
                and _const_str(n.targets[0].slice) == field:
            return True
        elif isinstance(n, ast.Call):
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "setdefault" and n.args \
                    and _const_str(n.args[0]) == field:
                return True
            if isinstance(n.func, ast.Name) and n.func.id == "dict" \
                    and any(kw.arg == field for kw in n.keywords):
                return True
    if depth <= 0:
        return False
    for n in ast.walk(fi.node):
        if isinstance(n, ast.Call):
            callee = cg.resolve_call(n.func, fi)
            if callee is not None and \
                    _writes_field(callee, field, cg, depth - 1, _seen):
                return True
    return False


def _resolve_carrier(cg, pattern):
    mod, _, func = pattern.rpartition(".")
    for fi in cg.info_by_node.values():
        if fi.name == func and \
                fi.module.modname.rsplit(".", 1)[-1] == mod:
            return fi
    return None


# ---------------------------------------------------------------------------
# the model + rules


@dataclasses.dataclass
class _Model:
    endpoints: list
    clients: list
    relays: list
    messages: list
    findings: list
    field_table: list


def _ep_regex(path):
    return re.compile("".join(".*" if ch == "*" else re.escape(ch)
                              for ch in path))


def _matches(ep, method, pattern):
    if ep.method != method:
        return False
    return _ep_regex(ep.path).fullmatch(pattern.replace("*", "\x00")) \
        is not None


def _build(project):
    cg = callgraph_mod.for_project(project)
    memo = {}
    endpoints = _extract_routes(cg, memo)
    clients, relays = _client_calls(cg)
    messages = _message_cases(cg)
    findings = []

    def path_of(qualname_layer):
        # findings anchor to the module file of the layer they concern
        for mi in cg.modules.values():
            if mi.modname.rsplit(".", 1)[-1] == qualname_layer:
                return mi.path
        return None

    # wire-unhandled-endpoint (HTTP side)
    for cc in clients:
        if not any(_matches(ep, cc.method, cc.path) for ep in endpoints):
            findings.append(Finding(
                path_of(cc.layer) or "", cc.line, "wire-unhandled-endpoint",
                f"{cc.caller} emits {cc.method} {cc.path} but no handler "
                f"routes it (known routes miss this method/path pair)"))

    # wire-dead-endpoint (HTTP side)
    for ep in endpoints:
        if (ep.method, ep.path) in EXTERNAL_ENDPOINTS:
            continue
        if not any(ep.method == cc.method and _matches(ep, cc.method, cc.path)
                   for cc in clients):
            findings.append(Finding(
                path_of(ep.layer) or "", ep.line, "wire-dead-endpoint",
                f"route {ep.method} {ep.path} ({ep.handler}) has no "
                f"in-repo client emission and is not declared in "
                f"protocol.EXTERNAL_ENDPOINTS"))

    # message planes: emitted-but-unhandled / handled-but-unemitted
    handled = {(m.key, m.value) for m in messages if m.side == "handle"}
    emitted = {(m.key, m.value) for m in messages if m.side == "emit"}
    for m in messages:
        if m.side == "emit" and (m.key, m.value) not in handled \
                and (m.key, m.value) not in ACK_MESSAGES:
            findings.append(Finding(
                path_of(m.layer) or "", m.line, "wire-unhandled-endpoint",
                f'{m.where} sends {{"{m.key}": "{m.value}"}} but no '
                f"dispatch case handles it (and it is not a declared "
                f"ack frame)"))
        elif m.side == "handle" and (m.key, m.value) not in emitted:
            findings.append(Finding(
                path_of(m.layer) or "", m.line, "wire-dead-endpoint",
                f'{m.where} dispatches on {{"{m.key}": "{m.value}"}} '
                f"but nothing in the repo emits that frame"))

    # wire-dropped-field
    field_table = []
    for spec in FIELD_SPECS:
        row = {"field": spec.field, "description": spec.description,
               "carriers": []}
        for pattern in spec.carriers:
            fi = _resolve_carrier(cg, pattern)
            entry = {"carrier": pattern,
                     "resolved": fi.qualname if fi else None,
                     "writes": None}
            if fi is not None:
                ok = _writes_field(fi, spec.field, cg)
                entry["writes"] = bool(ok)
                if not ok:
                    findings.append(Finding(
                        fi.module.path, fi.node.lineno, "wire-dropped-field",
                        f"carrier {fi.qualname} does not write contract "
                        f"field '{spec.field}' into any payload "
                        f"({spec.description})"))
            row["carriers"].append(entry)
        field_table.append(row)

    # wire-status-unhandled
    for cc in clients:
        if not cc.retried:
            continue
        consts = {c for c in cc.statuses if isinstance(c, int)}
        if "range" in cc.statuses or not consts \
                or not all(200 <= c < 300 for c in consts):
            continue
        for ep in endpoints:
            if not _matches(ep, cc.method, cc.path):
                continue
            perm = sorted(c for c in ep.statuses if isinstance(c, int)
                          and 400 <= c < 500 and c not in RETRYABLE_4XX)
            if perm:
                findings.append(Finding(
                    path_of(cc.layer) or "", cc.line,
                    "wire-status-unhandled",
                    f"{cc.caller} retries {cc.method} {cc.path} but only "
                    f"distinguishes status {sorted(consts)}; the route "
                    f"({ep.handler}) can answer permanent "
                    f"{perm} which would be retried as if transient"))
                break

    findings = [f for f in findings if f.path]
    return _Model(endpoints=endpoints, clients=clients, relays=relays,
                 messages=messages, findings=findings,
                 field_table=field_table)


def model_for(project):
    model = getattr(project, "_wireproto_model", None)
    if model is None:
        model = _build(project)
        project._wireproto_model = model
    return model


def protocol_dump(project):
    """The machine-readable contract: ``--format protocol``."""
    m = model_for(project)
    ext = [{"method": k[0], "path": k[1], "rationale": v}
           for k, v in sorted(EXTERNAL_ENDPOINTS.items())]
    acks = [{"key": k[0], "value": k[1], "rationale": v}
            for k, v in sorted(ACK_MESSAGES.items())]
    return {
        "version": 1,
        "endpoints": [e.as_dict() for e in sorted(
            m.endpoints, key=lambda e: (e.layer, e.method, e.path))],
        "clients": [c.as_dict() for c in sorted(
            m.clients, key=lambda c: (c.layer, c.caller, c.line))],
        "relays": [c.as_dict() for c in sorted(
            m.relays, key=lambda c: (c.layer, c.caller, c.line))],
        "messages": [c.as_dict() for c in sorted(
            m.messages, key=lambda c: (c.layer, c.side, c.value, c.line))],
        "fields": m.field_table,
        "external_endpoints": ext,
        "ack_messages": acks,
    }


class _WireRule(Rule):
    """All four rules share one cached protocol extraction per run."""

    def check(self, ctx):
        if ctx.project is None:
            return
        model = model_for(ctx.project)
        mine = _posix(ctx.path)
        for f in model.findings:
            if f.rule == self.name and _posix(f.path) == mine:
                yield f


@register
class UnhandledEndpointRule(_WireRule):
    name = "wire-unhandled-endpoint"
    description = ("a client emission (HTTP request or message frame) "
                   "that no server route or dispatch case handles")


@register
class DeadEndpointRule(_WireRule):
    name = "wire-dead-endpoint"
    description = ("a server route or dispatch case no in-repo client "
                   "emits, and not a declared operator-only surface")


@register
class DroppedFieldRule(_WireRule):
    name = "wire-dropped-field"
    description = ("a declared carrier (relay body, wire snapshot, job "
                   "record) stopped writing a propagated contract field "
                   "(priority/trace/seed/Idempotency-Key)")


@register
class StatusUnhandledRule(_WireRule):
    name = "wire-status-unhandled"
    description = ("a retried emission whose status checks cannot tell "
                   "a permanent 4xx from a transient failure, against a "
                   "route that really emits one")
