"""Thread-role race analyzer for the serving/orchestration plane.

PR 6 split ``serve.ContinuousBatcher`` into a device thread, a host
drain thread, and the HTTP handler threads that call its public
methods.  The ``locks`` rule sees lock/container pairing inside one
class but knows nothing about *which thread runs which method* — so it
cannot tell a single-thread free list (safe bare) from a counter two
threads bump (a lost-update race).  This analyzer infers the thread
topology and checks attribute sharing against it.

**Role inference** (zero annotations):

- every ``threading.Thread(target=self.X, ...)`` / ``Timer(_, self.X)``
  constructed anywhere in the class starts role ``thread:X``;
- ``do_GET``/``do_POST``-style methods are HTTP entry points (the
  stdlib server runs each on its own handler thread);
- public methods and private methods never referenced inside the class
  form the ``external`` role — the HTTP plane and test/driver callers.

Each role's **reachable set** is the closure over ``self.method(...)``
calls, propagating the lock set held across each call edge
(intersection over paths).  A call (or access) lexically under
``if threading.current_thread() is self.<t>:`` — the repo's
thread-identity-pinning idiom (``_retire``) — is attributed to the
pinned thread's role, not the caller's.

**Reported hazards** (rule ``thread-race``):

- a mutable container content-written in one role and content-accessed
  in another with no lock held at every one of those accesses
  (subscript/iteration/``len()``/``.get()`` can interleave with a
  concurrent resize);
- a read-modify-write (``self.x += 1``; ``self.x = f(self.x)``)
  executed from two or more roles without a common lock — the
  lost-update race.

Plain attribute rebinds cross-role stay silent (CPython rebind is
atomic; the repo's snapshot-publication idiom depends on it), as do
``queue.Queue``/``threading.*`` attributes (they ARE the sanctioned
handoff) and single-role attributes.  Findings anchor at the
attribute's ``__init__`` assignment so one
``# graftcheck: disable=thread-race`` documents a deliberately
unsynchronized attribute exactly once.

Rule ``lock-order`` reports cycles in the "acquired-while-holding"
digraph (lock-order inversion — deadlock risk), again following call
edges.

The role map doubles as the ``hostsync`` rule's hot-path oracle: a
thread role whose closure starts device copies
(``copy_to_host_async``) is the device-dispatch role, and its
exclusive methods are hot paths with no marker needed
(:func:`inferred_hotpaths`).
"""
from __future__ import annotations

import ast
import dataclasses

from . import callgraph as callgraph_mod
from .core import Finding, Rule, register
from .dataflow import call_name

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_SYNC_CTORS = {"Lock", "RLock", "Condition", "Event", "Semaphore",
               "BoundedSemaphore", "Barrier"}
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}
_THREAD_CTORS = {"Thread", "Timer"}
_HTTP_ENTRIES = {"do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD",
                 "do_PATCH"}
_MUTATOR_METHODS = {
    "setdefault", "update", "pop", "popitem", "append", "extend", "insert",
    "remove", "clear", "add", "discard", "popleft", "appendleft",
}
_CONTENT_METHODS = _MUTATOR_METHODS | {
    "get", "items", "keys", "values", "index", "count", "copy",
}
_CONSUMER_FNS = {"len", "list", "tuple", "sorted", "set", "dict", "sum",
                 "min", "max", "any", "all", "iter", "enumerate"}

# access kinds
READ, REBIND, RMW, CREAD, CWRITE = ("read", "rebind", "rmw",
                                    "content-read", "content-write")


def _self_attr(node):
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _ctor_base(value):
    if isinstance(value, ast.Call):
        name = call_name(value.func)
        if name is not None:
            return name.split(".")[-1]
    return None


def _refs_self_attr(expr, attr):
    for node in ast.walk(expr):
        if _self_attr(node) == attr:
            return True
    return False


def _pinned_thread_attr(test):
    """'X' when `test` is ``threading.current_thread() is self.X`` (either
    operand order, ``is`` or ``==``) — the thread-identity-pinning idiom."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.Eq))):
        return None
    sides = [test.left, test.comparators[0]]
    attr = next((a for a in map(_self_attr, sides) if a is not None), None)
    cur = next((s for s in sides if isinstance(s, ast.Call)
                and (call_name(s.func) or "").split(".")[-1]
                in ("current_thread", "currentThread")), None)
    return attr if (attr and cur is not None) else None


@dataclasses.dataclass
class Access:
    attr: str
    line: int
    kind: str
    locks: frozenset       # lexically-held self.<lock> attrs
    method: str
    pin: str = None        # thread-attr this access is pinned to


@dataclasses.dataclass
class MethodFacts:
    name: str
    node: object
    accesses: list
    calls: list            # (callee name, lexical locks, line, pin)
    acquisitions: list     # (lock, locks-held-before, line)
    has_device_copy: bool  # contains a .copy_to_host_async() call


@dataclasses.dataclass
class Role:
    name: str
    kind: str              # "thread" | "http" | "external"
    entries: tuple
    methods: dict = dataclasses.field(default_factory=dict)
    # method name -> frozenset of locks held at EVERY call path into it
    entry_locks: dict = dataclasses.field(default_factory=dict)
    device: bool = False   # reaches copy_to_host_async => device dispatch


@dataclasses.dataclass
class ClassModel:
    cls: object                          # callgraph.ClassInfo
    locks: set
    queues: set
    syncs: set
    containers: set
    init_lines: dict                     # attr -> __init__ assignment line
    facts: dict                          # method name -> MethodFacts
    roles: dict                          # role name -> Role
    thread_attr_targets: dict            # self-attr holding a Thread -> target


class _MethodWalker(ast.NodeVisitor):
    """Collect one method's attribute accesses, intra-class call edges,
    and lock acquisitions, tracking lexical `with self.<lock>` nesting
    and thread-identity pins."""

    def __init__(self, model, method_name):
        self.m = model
        self.method = method_name
        self.locks = []          # stack of held lock attrs
        self.pin = None
        self.accesses = []
        self.calls = []
        self.acquisitions = []
        self.has_device_copy = False
        self._skip = set()       # node ids already recorded via a parent

    def _held(self):
        return frozenset(self.locks)

    def _note(self, attr, node, kind):
        self.accesses.append(Access(attr, node.lineno, kind, self._held(),
                                    self.method, self.pin))

    # ---- locks -----------------------------------------------------------

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            expr = item.context_expr
            attr = _self_attr(expr)
            if attr is None and isinstance(expr, ast.Call):
                attr = _self_attr(expr.func)
            if attr in self.m.locks:
                acquired.append(attr)
            self.visit(expr)
        for lock in acquired:
            self.acquisitions.append((lock, self._held(), node.lineno))
            self.locks.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for lock in acquired:
            self.locks.pop()

    visit_AsyncWith = visit_With

    def visit_If(self, node):
        pin = _pinned_thread_attr(node.test)
        self.visit(node.test)
        if pin is not None and pin in self.m.thread_attr_targets:
            prev, self.pin = self.pin, pin
            for stmt in node.body:
                self.visit(stmt)
            self.pin = prev
            for stmt in node.orelse:
                self.visit(stmt)
            return
        self.generic_visit(node)

    # ---- accesses --------------------------------------------------------

    def visit_Assign(self, node):
        for tgt in node.targets:
            for t in ([tgt] if not isinstance(tgt, (ast.Tuple, ast.List))
                      else tgt.elts):
                attr = _self_attr(t)
                if attr is not None:
                    self._skip.add(id(t))
                    kind = (RMW if _refs_self_attr(node.value, attr)
                            else REBIND)
                    self._note(attr, node, kind)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        attr = _self_attr(node.target)
        if attr is not None:
            self._skip.add(id(node.target))
            self._note(attr, node, RMW)
        elif isinstance(node.target, ast.Subscript):
            attr = _self_attr(node.target.value)
            if attr is not None:
                self._skip.add(id(node.target))
                self._skip.add(id(node.target.value))
                self._note(attr, node, CWRITE)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if id(node) not in self._skip:
            attr = _self_attr(node.value)
            if attr is not None:
                self._skip.add(id(node.value))
                self._note(attr, node,
                           CWRITE if isinstance(node.ctx, (ast.Store,
                                                           ast.Del))
                           else CREAD)
        self.generic_visit(node)

    def visit_Call(self, node):
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "copy_to_host_async":
                self.has_device_copy = True
            # self.method(...): an intra-class call edge, not a data access
            meth = _self_attr(node.func)
            if meth is not None and meth in self.m.cls.methods:
                self._skip.add(id(node.func))
                self.calls.append((meth, self._held(), node.lineno,
                                   self.pin))
            owner = _self_attr(node.func.value)
            if owner is not None:
                self._skip.add(id(node.func.value))
                if owner in self.m.containers:
                    self._note(owner, node,
                               CWRITE if node.func.attr in _MUTATOR_METHODS
                               else CREAD)
        name = call_name(node.func)
        if name in _CONSUMER_FNS:
            for a in node.args:
                attr = _self_attr(a)
                if attr is not None:
                    self._skip.add(id(a))
                    self._note(attr, node, CREAD)
        self.generic_visit(node)

    def visit_For(self, node):
        attr = _self_attr(node.iter)
        if attr is not None:
            self._skip.add(id(node.iter))
            self._note(attr, node.iter, CREAD)
        self.generic_visit(node)

    def visit_comprehension(self, node):
        attr = _self_attr(node.iter)
        if attr is not None:
            self._skip.add(id(node.iter))
            self._note(attr, node.iter, CREAD)
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def visit_Attribute(self, node):
        if id(node) not in self._skip:
            attr = _self_attr(node)
            if attr is not None and isinstance(node.ctx, ast.Load):
                self._note(attr, node, READ)
        self.generic_visit(node)


def build_class_model(ci):
    """ClassModel (attribute classes, per-method facts, roles) for one
    callgraph.ClassInfo, or None when the class spawns no threads."""
    thread_targets = {}        # role-entry method name -> ctor line
    thread_attr_targets = {}   # self-attr holding the Thread -> target name
    for m in ci.methods.values():
        for node in ast.walk(m.node):
            if isinstance(node, ast.Call) and \
                    _ctor_base(node) in _THREAD_CTORS:
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = _self_attr(kw.value)
                if target is None and _ctor_base(node) == "Timer" \
                        and len(node.args) >= 2:
                    target = _self_attr(node.args[1])
                if target is not None and target in ci.methods:
                    thread_targets.setdefault(target, node.lineno)
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call) \
                    and _ctor_base(node.value) in _THREAD_CTORS:
                tgt_attr = next((a for a in map(_self_attr, node.targets)
                                 if a), None)
                target = next((_self_attr(kw.value)
                               for kw in node.value.keywords
                               if kw.arg == "target"), None)
                if tgt_attr and target:
                    thread_attr_targets[tgt_attr] = target
    if not thread_targets:
        return None

    locks, queues, syncs, containers, init_lines = set(), set(), set(), \
        set(), {}
    init = ci.methods.get("__init__")
    if init is not None:
        for node in ast.walk(init.node):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                init_lines.setdefault(attr, node.lineno)
                base = _ctor_base(node.value)
                if base in _LOCK_CTORS:
                    locks.add(attr)
                if base in _SYNC_CTORS:
                    syncs.add(attr)
                elif base in _QUEUE_CTORS:
                    queues.add(attr)
                elif base in _CONTAINER_CTORS or isinstance(
                        node.value, (ast.Dict, ast.List, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp)) or (
                        isinstance(node.value, ast.BinOp)
                        and isinstance(node.value.left, (ast.List,
                                                         ast.Dict))):
                    containers.add(attr)

    model = ClassModel(cls=ci, locks=locks, queues=queues, syncs=syncs,
                       containers=containers, init_lines=init_lines,
                       facts={}, roles={},
                       thread_attr_targets=thread_attr_targets)

    for name, fi in ci.methods.items():
        if name == "__init__":
            continue           # construction happens-before sharing
        w = _MethodWalker(model, name)
        for stmt in fi.node.body:
            w.visit(stmt)
        model.facts[name] = MethodFacts(
            name=name, node=fi.node, accesses=w.accesses, calls=w.calls,
            acquisitions=w.acquisitions, has_device_copy=w.has_device_copy)

    # ---- roles -----------------------------------------------------------
    referenced = set()
    for name, fi in ci.methods.items():
        for node in ast.walk(fi.node):
            attr = _self_attr(node)
            if attr is not None and attr in ci.methods and attr != name:
                referenced.add(attr)
    roles = {}
    for target in sorted(thread_targets):
        roles[f"thread:{target}"] = Role(name=f"thread:{target}",
                                         kind="thread", entries=(target,))
    http = tuple(sorted(n for n in ci.methods if n in _HTTP_ENTRIES))
    if http:
        roles["http"] = Role(name="http", kind="http", entries=http)
    external = tuple(sorted(
        n for n in ci.methods
        if n != "__init__" and n not in thread_targets
        and n not in _HTTP_ENTRIES
        and (not n.startswith("_") or n not in referenced)))
    if external:
        roles["external"] = Role(name="external", kind="external",
                                 entries=external)

    for role in roles.values():
        _propagate(model, role)
        role.device = any(model.facts[m].has_device_copy
                          for m in role.methods)
    model.roles = roles
    return model


def _propagate(model, role):
    """Fill `role.methods`/`entry_locks`: reachable closure over intra-
    class call edges, entry-lock sets merged by intersection across call
    paths.  Pinned call edges only traverse when the pin names this
    role's thread — and they SEED this role from any caller, since the
    identity check guarantees the callee runs on the pinned thread."""
    pending = {e: frozenset() for e in role.entries if e in model.facts}
    if role.kind == "thread":
        tname = role.entries[0] if role.entries else None
        for facts in model.facts.values():
            for callee, lex_locks, _line, pin in facts.calls:
                if (pin is not None and callee in model.facts
                        and model.thread_attr_targets.get(pin) == tname):
                    pending[callee] = (pending[callee] & lex_locks
                                       if callee in pending else lex_locks)
    while pending:
        name, held = pending.popitem()
        if name in role.entry_locks:
            merged = role.entry_locks[name] & held
            if merged == role.entry_locks[name]:
                continue
            role.entry_locks[name] = merged
        else:
            role.entry_locks[name] = held
        role.methods[name] = model.facts[name]
        for callee, lex_locks, _line, pin in model.facts[name].calls:
            if pin is not None:
                target = model.thread_attr_targets.get(pin)
                if role.name != f"thread:{target}":
                    continue
            if callee in model.facts:
                pending[callee] = (role.entry_locks[name] | lex_locks) \
                    if callee not in pending \
                    else pending[callee] & (role.entry_locks[name]
                                            | lex_locks)


def _role_of_pin(model, pin):
    target = model.thread_attr_targets.get(pin)
    return f"thread:{target}" if target else None


def iter_attr_accesses(model):
    """Yield (role_name, Access, effective_locks) over every role, with
    entry-held locks folded in and pinned accesses re-attributed."""
    for rname, role in model.roles.items():
        for mname, facts in role.methods.items():
            base = role.entry_locks.get(mname, frozenset())
            for acc in facts.accesses:
                eff_role = rname
                if acc.pin is not None:
                    pinned = _role_of_pin(model, acc.pin)
                    if pinned is not None and pinned != rname:
                        if pinned in model.roles:
                            eff_role = pinned
                        else:
                            continue
                yield eff_role, acc, base | acc.locks


def class_model(ctx, cls_node):
    """Build (and cache on the project) the ClassModel for `cls_node`."""
    project = ctx.project
    cache = getattr(project, "_class_models", None)
    if cache is None:
        cache = project._class_models = {}
    key = id(cls_node)
    if key not in cache:
        cg = callgraph_mod.for_project(project)
        mi = cg.by_path.get(callgraph_mod._posix(ctx.path))
        ci = None
        if mi is not None:
            ci = next((c for c in mi.classes.values()
                       if c.node is cls_node), None)
        cache[key] = build_class_model(ci) if ci is not None else None
    return cache[key]


def inferred_hotpaths(ctx):
    """Function nodes covered by hostsync WITHOUT a marker: methods
    reachable exclusively from a device-dispatch thread role (a thread
    whose closure calls ``copy_to_host_async``).  Methods also reachable
    from the host/external roles are shared host-side code and stay
    uncovered."""
    out = {}
    if ctx.tree is None or ctx.project is None:
        return out
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        model = class_model(ctx, node)
        if model is None:
            continue
        device, other = set(), set()
        for role in model.roles.values():
            (device if role.device else other).update(role.methods)
        for name in device - other:
            out[id(model.facts[name].node)] = model.facts[name].node
    return out


@register
class ThreadRaceRule(Rule):
    name = "thread-race"
    description = ("attribute shared across inferred thread roles without "
                   "a common lock (container resize / lost-update races)")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        if ctx.project is None:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                model = class_model(ctx, node)
                if model is not None and len(model.roles) >= 2:
                    yield from self._check_model(ctx, node, model)

    def _check_model(self, ctx, cls, model):
        by_attr = {}
        for rname, acc, locks in iter_attr_accesses(model):
            if acc.attr in model.queues or acc.attr in model.syncs:
                continue
            by_attr.setdefault(acc.attr, []).append((rname, acc, locks))

        for attr in sorted(by_attr):
            entries = by_attr[attr]
            roles = {r for r, _, _ in entries}
            if len(roles) < 2:
                continue
            anchor = model.init_lines.get(
                attr, min(a.line for _, a, _ in entries))

            if attr in model.containers:
                content = [(r, a, lk) for r, a, lk in entries
                           if a.kind in (CREAD, CWRITE)]
                cw_roles = {r for r, a, _ in content if a.kind == CWRITE}
                c_roles = {r for r, _, _ in content}
                if cw_roles and len(c_roles) > 1:
                    common = None
                    for _, _, lk in content:
                        common = lk if common is None else common & lk
                    if not common:
                        ex = next((f"{a.method}:{a.line}"
                                   for _, a, lk in content if not lk),
                                  f"{content[0][1].method}")
                        yield Finding(
                            ctx.path, anchor, self.name,
                            f"{cls.name}.{attr}: container content-written "
                            f"in role(s) {'/'.join(sorted(cw_roles))} and "
                            f"accessed from {'/'.join(sorted(c_roles))} "
                            f"with no common lock (e.g. unguarded at "
                            f"{ex}); a concurrent resize can interleave — "
                            "guard every content access or hand off "
                            "through a queue")
                continue

            rmw = [(r, a, lk) for r, a, lk in entries if a.kind == RMW]
            rmw_roles = {r for r, _, _ in rmw}
            if len(rmw_roles) > 1:
                common = None
                for _, _, lk in rmw:
                    common = lk if common is None else common & lk
                if not common:
                    sites = sorted({f"{a.method}:{a.line}"
                                    for _, a, _ in rmw})
                    yield Finding(
                        ctx.path, anchor, self.name,
                        f"{cls.name}.{attr}: read-modify-write from "
                        f"roles {'/'.join(sorted(rmw_roles))} "
                        f"({', '.join(sites[:4])}) with no common lock — "
                        "concurrent increments lose updates; use "
                        "metrics.Counters or guard with one lock")


@register
class LockOrderRule(Rule):
    name = "lock-order"
    description = ("lock acquisition cycles across thread roles "
                   "(A-then-B in one path, B-then-A in another)")
    kind = "semantic"
    scope = "package"

    def check(self, ctx):
        if ctx.project is None:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                model = class_model(ctx, node)
                if model is not None:
                    yield from self._check_model(ctx, node, model)

    def _check_model(self, ctx, cls, model):
        edges = {}        # lock -> {lock2: first line seen}
        for role in model.roles.values():
            for mname, facts in role.methods.items():
                base = role.entry_locks.get(mname, frozenset())
                for lock, held, line in facts.acquisitions:
                    for h in base | held:
                        if h != lock:
                            edges.setdefault(h, {}).setdefault(lock, line)
        reported = set()
        for a in sorted(edges):
            for b in sorted(edges[a]):
                if a in edges.get(b, ()) and frozenset((a, b)) not in reported:
                    reported.add(frozenset((a, b)))
                    yield Finding(
                        ctx.path, edges[a][b], "lock-order",
                        f"{cls.name}: self.{b} acquired while holding "
                        f"self.{a} (line {edges[a][b]}) and self.{a} while "
                        f"holding self.{b} (line {edges[b][a]}) — lock-"
                        "order inversion; pick one order everywhere")
