"""Sentinel markers placed in data queues (maps reference marker.py:1-18).

The feeder side pushes these into the ``input`` queue to signal structural
events to the consumer (`feed.DataFeed`):

- ``None`` (not a class here, by convention) — end of the entire feed.
- ``EndPartition`` — end of one upstream partition; used during inference so
  the consumer can flush exactly one result per input record before results
  for the next partition begin (reference: TFSparkNode.py:541-546).
- ``Chunk`` — a TPU-native addition: a batched list of records pushed as ONE
  queue item.  Per-item pickled queue puts are the reference design's
  throughput ceiling (SURVEY.md §7); chunked transfer amortizes IPC cost.
"""


class Marker:
    """Base class for data-queue sentinels."""


class EndPartition(Marker):
    """Marks the end of one input partition within the feed."""


class Progress(Marker):
    """In-band consumption checkpoint (feed-offset resume, net-new).

    The feeder interleaves these with record chunks; when the consumer
    (`feed.DataFeed`) dequeues one, every record before it has been
    consumed, so ``offset`` is a consumption-CONFIRMED high-water mark
    for partition ``pid`` — exactly what `cluster.run_elastic` needs to
    skip already-delivered records on relaunch without ever skipping an
    unconsumed one."""

    __slots__ = ("pid", "offset")

    def __init__(self, pid, offset):
        self.pid = int(pid)
        self.offset = int(offset)

    def __repr__(self):
        return f"Progress(pid={self.pid}, offset={self.offset})"


class Chunk:
    """A list of records transported as a single queue item.

    Not a Marker: it carries payload.  ``items`` is a plain list so it pickles
    cheaply through the multiprocessing proxy.
    """

    __slots__ = ("items",)

    def __init__(self, items):
        self.items = items

    def __len__(self):
        return len(self.items)

    def __repr__(self):
        return f"Chunk(n={len(self.items)})"


class PackedChunk:
    """A record chunk transported as contiguous numpy buffers.

    Pickling a Chunk of N records x F python-object fields costs O(N*F)
    object serialization on both sides of the queue — the dominant feed
    cost.  Packing the chunk first makes the same transfer a handful of
    buffer copies.  Three layouts:

    - field records (``row_type`` tuple/list, ``matrix`` False):
      ``columns`` holds one [N, ...] array per record field — the
      (image_array, label) shape.
    - wide flat records (``row_type`` tuple/list, ``matrix`` True):
      ``columns`` is a single [N, F] matrix (per-field arrays would mean F
      tiny objects each way); fields share one promoted dtype.
    - single-value records (``row_type`` None): ``columns[0]`` is the [N,
      ...] stack.
    """

    __slots__ = ("columns", "row_type", "matrix")

    def __init__(self, columns, row_type, matrix=False):
        self.columns = columns
        self.row_type = row_type
        self.matrix = matrix

    def __len__(self):
        return len(self.columns[0])

    def __repr__(self):
        return (f"PackedChunk(n={len(self)}, fields={len(self.columns)}, "
                f"matrix={self.matrix}, "
                f"row_type={self.row_type and self.row_type.__name__})")


# Field-record packing is per-field; past this many fields a flat scalar
# record packs as one matrix instead (F small arrays each way would cost
# more than they save).
_MAX_FIELDS = 16


def pack_records(items):
    """Return a PackedChunk for a uniform numeric record list, or a plain
    Chunk when the records don't pack (ragged, object-dtype, mixed types).

    Packable shapes: every record a scalar/ndarray of one dtype+shape;
    every record a same-length tuple/list of <= 16 fields each stacking to
    a non-object array; or wide flat scalar rows, packed as one [N, F]
    matrix (fields are promoted to a common dtype there).
    """
    import numpy as np

    if not items:
        return Chunk(items)
    first = items[0]
    try:
        # EXACT tuple/list only: subclasses (namedtuple, pyspark Row, ...)
        # don't reconstruct from an iterable, so they ride plain Chunks
        if type(first) in (tuple, list):
            row_type = type(first)
            nf = len(first)
            if any(type(r) is not row_type or len(r) != nf
                   for r in items):
                return Chunk(items)
            if nf <= _MAX_FIELDS:
                cols = tuple(np.asarray([r[i] for r in items])
                             for i in range(nf))
                if any(c.dtype == object for c in cols):
                    return Chunk(items)
                return PackedChunk(cols, row_type)
            mat = np.asarray(items)
            if mat.dtype == object or mat.ndim < 2:
                return Chunk(items)
            return PackedChunk((mat,), row_type, matrix=True)
        # single-value records: require ONE exact python scalar type (so
        # values round-trip via tolist without int->float promotion) or
        # uniform ndarrays/np scalars (which list() restores exactly);
        # anything else (tuple subclasses, decimals, ...) rides a Chunk
        t0 = type(first)
        if not (t0 in (int, float, bool)
                or isinstance(first, (np.ndarray, np.generic))):
            return Chunk(items)
        if any(type(x) is not t0 for x in items):
            return Chunk(items)
        col = np.asarray(items)
        if col.dtype == object:
            return Chunk(items)
        if t0 in (int, float, bool):
            return PackedChunk((col,), t0)  # row_type = scalar type:
            # materialize via tolist() -> exact python scalars back
        return PackedChunk((col,), None)
    except (ValueError, TypeError, OverflowError):
        return Chunk(items)
