"""Sentinel markers placed in data queues (maps reference marker.py:1-18).

The feeder side pushes these into the ``input`` queue to signal structural
events to the consumer (`feed.DataFeed`):

- ``None`` (not a class here, by convention) — end of the entire feed.
- ``EndPartition`` — end of one upstream partition; used during inference so
  the consumer can flush exactly one result per input record before results
  for the next partition begin (reference: TFSparkNode.py:541-546).
- ``Chunk`` — a TPU-native addition: a batched list of records pushed as ONE
  queue item.  Per-item pickled queue puts are the reference design's
  throughput ceiling (SURVEY.md §7); chunked transfer amortizes IPC cost.
"""


class Marker:
    """Base class for data-queue sentinels."""


class EndPartition(Marker):
    """Marks the end of one input partition within the feed."""


class Chunk:
    """A list of records transported as a single queue item.

    Not a Marker: it carries payload.  ``items`` is a plain list so it pickles
    cheaply through the multiprocessing proxy.
    """

    __slots__ = ("items",)

    def __init__(self, items):
        self.items = items

    def __len__(self):
        return len(self.items)

    def __repr__(self):
        return f"Chunk(n={len(self.items)})"
