"""Cluster rendezvous: reservation server + client.

Maps the reference's cleanest component (reference: reservation.py:31-301) with
two deliberate re-designs for the TPU build:

1. **msgpack framing, not pickle.**  The reference exchanges pickled dicts
   (reference: reservation.py:68-97); pickle over TCP executes arbitrary code
   from untrusted peers.  We keep the 4-byte big-endian length prefix but the
   payload is msgpack (bytes-safe, no code execution).

2. **The server hands out JAX-distributed bootstrap info.**  The reference's
   clients scout free ports and the server aggregates them into a TF
   ClusterSpec.  On TPU, the XLA runtime owns interconnect setup, so nodes
   register host metadata and the aggregate reservation list yields
   ``(coordinator_addr, num_processes, process_id)`` for
   ``jax.distributed.initialize`` (SURVEY.md §2.4).

Message types (reference: reservation.py:130-146 had REG/QUERY/QINFO/STOP):

- ``REG``   {node: {...meta}}          -> ``OK``
- ``QUERY`` {}                         -> ``QUERY`` {done: bool, count: int}
- ``QINFO`` {}                         -> ``QINFO`` {nodes: [...]}
- ``ERROR`` {node, error: str}         -> ``OK``       (net-new: failure detection)
- ``BEAT``  {executor_id}              -> ``OK``       (net-new: liveness heartbeat)
- ``BYE``   {executor_id}              -> ``OK``       (net-new: announced exit, so
                                          the monitor won't flag this node)
- ``PROGRESS`` {offsets: {pid: off}}   -> ``OK``       (net-new: feed high-water
                                          marks, consumed-record offsets per
                                          partition; cluster.run_elastic reads
                                          them to bound duplicate delivery on
                                          relaunch)
- ``STOP``  {}                         -> ``OK``, server shuts down
"""
import logging
import os
import select
import socket
import struct
import threading
import time

import msgpack

from . import faults, util

logger = logging.getLogger(__name__)

# Env overrides for the server bind address (reference: reservation.py:25-26).
SERVER_HOST_ENV = "TFOS_TPU_SERVER_HOST"
SERVER_PORT_ENV = "TFOS_TPU_SERVER_PORT"

CONNECT_RETRIES = 3
CONNECT_RETRY_DELAY_SECS = 2
CONNECT_RETRY_DELAY_CAP_SECS = 15.0
CONNECT_TIMEOUT_SECS = 30.0
RPC_TIMEOUT_SECS = 60.0


def _backoff_delay(attempt, base, cap):
    """Capped exponential delay before connect retry `attempt` (0-based):
    base, 2*base, 4*base, ... never exceeding `cap`.  Delegates to the
    package-wide :class:`util.RetryPolicy` schedule (jitterless here:
    tests pin exact delays through the module knobs)."""
    return util.RetryPolicy(attempts=2, base_delay=base,
                            cap_delay=cap).delay(attempt)


class Reservations:
    """Thread-safe registry of node reservations (reference: reservation.py:31-65)."""

    def __init__(self, required):
        self.required = required
        self._lock = threading.RLock()
        self._nodes = []
        self._errors = []

    def add(self, meta):
        with self._lock:
            self._nodes.append(meta)

    def done(self):
        with self._lock:
            return len(self._nodes) >= self.required

    def get(self):
        with self._lock:
            return list(self._nodes)

    def remaining(self):
        with self._lock:
            return self.required - len(self._nodes)

    def add_error(self, err):
        with self._lock:
            self._errors.append(err)

    def get_errors(self):
        with self._lock:
            return list(self._errors)


class MessageSocket:
    """Length-prefixed msgpack messages over a socket (reference: reservation.py:68-97)."""

    MAX_FRAME_BYTES = 64 * 1024 * 1024  # rendezvous messages are small

    def receive(self, sock):
        header = self._recv_exact(sock, 4)
        (length,) = struct.unpack(">I", header)
        if length > self.MAX_FRAME_BYTES:
            raise ValueError(f"frame of {length} bytes exceeds protocol limit")
        payload = self._recv_exact(sock, length)
        return msgpack.unpackb(payload, raw=False)

    def send(self, sock, msg):
        payload = msgpack.packb(msg, use_bin_type=True)
        header = struct.pack(">I", len(payload))
        if len(payload) >= (1 << 16):
            # large frames (kvtransfer page blocks ride this framing):
            # two sendalls instead of materializing a header+payload copy
            sock.sendall(header)
            sock.sendall(payload)
        else:
            # small frames (rendezvous RPCs): one write, one segment
            sock.sendall(header + payload)

    @staticmethod
    def _recv_exact(sock, n):
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("socket closed mid-message")
            buf.extend(chunk)
        return bytes(buf)


class Server(MessageSocket):
    """Driver-side rendezvous server (reference: reservation.py:100-231).

    Runs a selector loop on a daemon thread; the driver blocks in
    `await_reservations` until all `count` nodes registered (or error/timeout).
    """

    def __init__(self, count):
        assert count > 0
        self.reservations = Reservations(count)
        self.done = threading.Event()
        self._sock = None
        # Heartbeat state (net-new failure detection, SURVEY.md §5: the
        # reference has none and jax.distributed historically hangs on
        # silent peer loss; the coordinator must notice instead).
        self._beats = {}        # executor_id -> last beat monotonic time
        self._finished = set()  # executor_ids that sent BYE (normal exit)
        self._progress = {}     # partition id -> consumed-record high water
        self._flagged = set()   # executor_ids already reported dead
        self._beat_lock = threading.Lock()

    def start(self, host=None, ports=None):
        """Bind and start the listener thread; return (host, port).

        `host`/`ports` (a candidate-port list) override the env knobs —
        a fleet gateway binds an operator-chosen registry address while
        the training driver keeps the env-driven path."""
        if host is None:
            host = os.environ.get(SERVER_HOST_ENV, util.get_ip_address())
        if ports is None:
            port_spec = os.environ.get(SERVER_PORT_ENV)
            ports = util.parse_port_spec(port_spec) if port_spec else None
        self._sock = util.bind_socket(host, ports)
        addr = (host, self._sock.getsockname()[1])
        logger.info("reservation server listening on %s", addr)
        t = threading.Thread(target=self._serve, name="reservation-server", daemon=True)
        t.start()
        return addr

    @property
    def address(self):
        host, port = self._sock.getsockname()
        return (host, port)

    def _serve(self):
        conns = [self._sock]
        while not self.done.is_set():
            try:
                readable, _, _ = select.select(conns, [], [], 1.0)
            except OSError:
                break  # listener closed during shutdown
            for s in readable:
                if s is self._sock:
                    try:
                        client, _ = self._sock.accept()
                        try:
                            # A peer that stalls mid-frame must not wedge the
                            # single serve thread: bound each read so the peer
                            # is dropped instead (select readiness only
                            # guarantees >=1 byte, not a whole frame).
                            client.settimeout(10.0)
                            conns.append(client)
                        except OSError:
                            client.close()
                            raise
                    except OSError:
                        pass
                else:
                    try:
                        msg = self.receive(s)
                        self._dispatch(s, msg)
                    except Exception as e:
                        # A malformed frame from one peer must never kill the
                        # rendezvous loop for everyone else: drop that peer.
                        if not isinstance(e, (ConnectionError, OSError)):
                            logger.warning("dropping connection after bad message: %s", e)
                        conns.remove(s)
                        s.close()
        for s in conns:
            s.close()

    def _dispatch(self, sock, msg):
        mtype = msg.get("type")
        if mtype == "REG":
            self.reservations.add(msg["node"])
            logger.info("registered node: %s", msg["node"])
            self.send(sock, {"type": "OK"})
        elif mtype == "QUERY":
            self.send(sock, {
                "type": "QUERY",
                "done": self.reservations.done(),
                "count": len(self.reservations.get()),
                "required": self.reservations.required,
            })
        elif mtype == "QINFO":
            self.send(sock, {"type": "QINFO", "nodes": self.reservations.get()})
        elif mtype == "BEAT":
            with self._beat_lock:
                self._beats[msg.get("executor_id")] = time.monotonic()
            self.send(sock, {"type": "OK"})
        elif mtype == "BYE":
            with self._beat_lock:
                self._finished.add(msg.get("executor_id"))
            logger.info("node %s finished (BYE)", msg.get("executor_id"))
            self.send(sock, {"type": "OK"})
        elif mtype == "PROGRESS":
            with self._beat_lock:
                for pid, off in (msg.get("offsets") or {}).items():
                    pid = int(pid)
                    self._progress[pid] = max(self._progress.get(pid, 0),
                                              int(off))
            self.send(sock, {"type": "OK"})
        elif mtype == "ERROR":
            logger.error("node reported error: %s", msg.get("error"))
            self.reservations.add_error(
                {"node": msg.get("node"), "error": msg.get("error", "")})
            self.send(sock, {"type": "OK"})
        elif mtype == "STOP":
            logger.info("received STOP, shutting down reservation server")
            self.send(sock, {"type": "OK"})
            self.stop()
        else:
            self.send(sock, {"type": "ERR", "error": f"unknown message {mtype!r}"})

    def await_reservations(self, timeout=600, status=None):
        """Block until all nodes registered (reference: reservation.py:113-128).

        `status` is an optional mutable mapping with an 'error' key set by the
        launch thread (reference TFCluster's tf_status) — aborts early if set.
        Node-reported ERROR messages abort as well (net-new failure detection).
        """
        deadline = time.time() + timeout
        while not self.reservations.done():
            if status is not None and status.get("error"):
                raise RuntimeError(f"cluster launch failed: {status['error']}")
            errs = self.reservations.get_errors()
            if errs:
                raise RuntimeError(f"node(s) failed during startup: {errs}")
            logger.info("waiting for %d reservations", self.reservations.remaining())
            if time.time() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {self.reservations.remaining()} "
                    f"of {self.reservations.required} reservations")
            time.sleep(1)
        logger.info("all %d reservations completed", self.reservations.required)
        return self.reservations.get()

    def progress_snapshot(self):
        """Consumed-record high-water marks {partition id: offset} reported
        via PROGRESS (feed-offset resume, cluster.run_elastic)."""
        with self._beat_lock:
            return dict(self._progress)

    def seed_beat(self, executor_id):
        """Grant `executor_id` a fresh liveness window (as if it just
        beat).  Registration-time seeding: a node whose heartbeat thread
        has not connected yet must not read as instantly dead."""
        with self._beat_lock:
            self._beats[executor_id] = time.monotonic()

    def last_beats(self):
        """Snapshot of {executor_id: last-beat monotonic time}.  The
        fleet gateway's ejection/re-admission monitor reads this (it
        needs beat *recency* for re-admission, not just `dead_nodes`)."""
        with self._beat_lock:
            return dict(self._beats)

    def dead_nodes(self, timeout):
        """Executor ids that heartbeated once but have been silent for
        > `timeout` seconds and did not announce a normal exit (BYE)."""
        now = time.monotonic()
        with self._beat_lock:
            return [eid for eid, t in self._beats.items()
                    if eid not in self._finished and now - t > timeout]

    def finished_ids(self):
        """Snapshot of executor ids that announced a normal exit (BYE) —
        the driver's signal that a node's user fn returned (the analog of
        the reference polling Spark's statusTracker for finished worker
        tasks, TFCluster.py:154-169)."""
        with self._beat_lock:
            return set(self._finished)

    def start_monitor(self, heartbeat_timeout, interval=None, expected=None):
        """Flag silently-dead nodes as cluster errors (net-new vs the
        reference, which only noticed errors nodes *reported*; a SIGKILLed
        or OOMed training process reports nothing). Each dead node is
        reported once, through the same error channel `ERROR` messages use,
        so the driver's existing error surfacing aborts the job.

        `expected` seeds the beat table with every registered executor id
        (as if each had just beaten): a node whose heartbeat client never
        managed to connect is otherwise invisible to `dead_nodes` — exactly
        the unmonitored-node hole this monitor exists to close.  Seeding
        grants each node one full timeout window to start beating.
        """
        if expected:
            now = time.monotonic()
            with self._beat_lock:
                for eid in expected:
                    self._beats.setdefault(eid, now)

        def _watch():
            poll = interval or max(heartbeat_timeout / 4.0, 1.0)
            while not self.done.is_set():
                for eid in self.dead_nodes(heartbeat_timeout):
                    with self._beat_lock:
                        if eid in self._flagged:
                            continue
                        self._flagged.add(eid)
                    logger.error("node %s heartbeat lost (> %ss silent)",
                                 eid, heartbeat_timeout)
                    self.reservations.add_error(
                        {"node": {"executor_id": eid},
                         "error": f"heartbeat lost for executor {eid} "
                                  f"(silent > {heartbeat_timeout}s)"})
                self.done.wait(poll)

        t = threading.Thread(target=_watch, name="heartbeat-monitor",
                             daemon=True)
        t.start()
        return t

    def stop(self):
        self.done.set()
        try:
            self._sock.close()
        except OSError:
            pass


class Client(MessageSocket):
    """Executor-side rendezvous client (reference: reservation.py:234-301)."""

    def __init__(self, server_addr, connect=True, connect_timeout=None,
                 rpc_timeout=None, retries=None, retry_delay=None,
                 retry_delay_cap=None):
        """`connect=False` defers the main-socket connect to the first
        RPC — used by heartbeat-only clients, whose beat thread makes its
        own connections and must start (and keep retrying) even while the
        server is briefly unreachable.

        The timeout knobs bound how long a dead or wedged server can
        stall this client (a serving replica registering with a fleet
        gateway must fail fast, not hang startup): `connect_timeout` /
        `rpc_timeout` are per-dial socket timeouts, `retries` bounds the
        connect attempts, and `retry_delay`/`retry_delay_cap` shape the
        capped exponential backoff between them.  ``None`` defers to the
        module defaults AT CALL TIME (so tests may monkeypatch them)."""
        self.server_addr = (server_addr[0], int(server_addr[1]))
        self._connect_timeout = connect_timeout
        self._rpc_timeout = rpc_timeout
        self._retries = retries
        self._retry_delay = retry_delay
        self._retry_delay_cap = retry_delay_cap
        self._sock = self._connect() if connect else None
        self._lock = threading.Lock()

    def _dial(self, connect_timeout, rpc_timeout):
        """One fresh connection to the server.  The per-RPC timeout bounds
        receive(): if the server host dies without RST, a blocked read must
        not hang the executor forever."""
        faults.check("reservation.dial")
        s = socket.create_connection(self.server_addr,
                                     timeout=connect_timeout)
        try:
            s.settimeout(rpc_timeout)
        except OSError:
            s.close()
            raise
        return s

    def _effective_timeouts(self):
        """(connect_timeout, rpc_timeout) with module defaults filled in.
        Rendezvous RPCs complete in milliseconds; the 60s default covers
        a driver briefly stalled by GC/oversubscription."""
        ct = (self._connect_timeout if self._connect_timeout is not None
              else CONNECT_TIMEOUT_SECS)
        rt = (self._rpc_timeout if self._rpc_timeout is not None
              else RPC_TIMEOUT_SECS)
        return ct, rt

    def _connect(self):
        retries = self._retries if self._retries is not None else \
            CONNECT_RETRIES
        base = (self._retry_delay if self._retry_delay is not None
                else CONNECT_RETRY_DELAY_SECS)
        cap = (self._retry_delay_cap if self._retry_delay_cap is not None
               else CONNECT_RETRY_DELAY_CAP_SECS)
        ct, rt = self._effective_timeouts()
        policy = util.RetryPolicy(attempts=max(1, retries),
                                  base_delay=base, cap_delay=cap)
        last = None
        for attempt in policy.sleeps():
            try:
                return self._dial(connect_timeout=ct, rpc_timeout=rt)
            except OSError as e:
                last = e
                logger.warning("connect to %s failed (%s); retry %d/%d",
                               self.server_addr, e, attempt + 1, retries)
        raise ConnectionError(f"could not reach reservation server at {self.server_addr}: {last}")

    def _request(self, msg):
        with self._lock:
            if self._sock is None:
                self._sock = self._connect()
            try:
                faults.check("reservation.rpc")
                self.send(self._sock, msg)
                return self.receive(self._sock)
            except Exception:
                # A timed-out or half-sent RPC leaves the framed stream
                # mid-message: the socket is wedged for every later call.
                # Close and drop it so the next RPC redials cleanly.
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                raise

    def register(self, node_meta):
        return self._request({"type": "REG", "node": node_meta})

    def query(self):
        return self._request({"type": "QUERY"})

    def get_reservations(self):
        return self._request({"type": "QINFO"})["nodes"]

    def await_reservations(self, timeout=600):
        """Poll until the cluster is fully registered; return the node list."""
        deadline = time.time() + timeout
        while True:
            resp = self.query()
            if resp.get("done"):
                return self.get_reservations()
            if time.time() > deadline:
                raise TimeoutError("timed out awaiting cluster reservations")
            time.sleep(1)

    def report_error(self, node_meta, error):
        try:
            return self._request({"type": "ERROR", "node": node_meta, "error": str(error)})
        except OSError:
            logger.warning("could not report error to reservation server")

    def request_stop(self):
        try:
            return self._request({"type": "STOP"})
        except (ConnectionError, OSError):
            return {"type": "OK"}  # server already gone

    def send_progress(self, offsets):
        """Report consumed-record high-water marks {partition: offset};
        best-effort (a lost report only widens the duplicate window)."""
        if not offsets:
            return
        try:
            # keys stringified: msgpack's strict_map_key (the receive-side
            # default) rejects int map keys; the server re-ints them
            return self._request({"type": "PROGRESS",
                                  "offsets": {str(p): int(o)
                                              for p, o in offsets.items()}})
        except (ConnectionError, OSError):
            logger.warning("could not report feed progress")

    def start_heartbeat(self, executor_id, interval=5.0):
        """Beat on a daemon thread until `stop_heartbeat`/`close`/`bye`.

        Uses a DEDICATED connection: the beat thread must not interleave
        frames with request/response traffic on the main socket.  An
        unreachable server never ends the thread — it retries with capped
        backoff until explicitly stopped.  Giving up would be worse than
        useless: the node may be training fine through a transient blip,
        and a (possibly restarted) monitor would then flag a healthy node
        as dead and abort the whole job.
        """
        self._hb_stop = getattr(self, "_hb_stop", None) or threading.Event()
        self._hb_stop.clear()

        def _beat():
            # Single-attempt reconnects (NOT the Client() constructor, whose
            # retry/backoff sleeps ignore the stop event): stop_heartbeat
            # must end this thread within ~one beat interval.
            hb = None
            ct, rt = self._effective_timeouts()
            while not self._hb_stop.is_set():
                try:
                    faults.check("reservation.heartbeat")
                    if hb is None:
                        hb = self._dial(connect_timeout=min(5.0, ct),
                                        rpc_timeout=min(10.0, rt))
                    self.send(hb, {"type": "BEAT",
                                   "executor_id": executor_id})
                    self.receive(hb)
                except (ConnectionError, OSError):
                    if hb is not None:
                        try:
                            hb.close()
                        except OSError:
                            pass
                        hb = None
                # Constant cadence, no backoff: a BEAT is one tiny frame,
                # and widening the gap during an outage is exactly when
                # liveness proof is most urgent — backoff would let a
                # ~heartbeat_timeout/2 blip trip the monitor.
                self._hb_stop.wait(interval)
            if hb is not None:
                try:
                    hb.close()
                except OSError:
                    pass

        t = threading.Thread(target=_beat, name=f"heartbeat-{executor_id}",
                             daemon=True)
        t.start()
        self._hb_thread = t
        return t

    def stop_heartbeat(self):
        ev = getattr(self, "_hb_stop", None)
        if ev is not None:
            ev.set()

    def bye(self, executor_id):
        """Announce a normal exit so the monitor won't flag this node.

        A lost BYE would convert a successful node into a false
        "heartbeat lost" job failure (beats stop regardless), so it never
        touches the main socket — which sat idle for the whole training
        run and may have been dropped by NAT/conntrack — and uses only
        fresh short-timeout connections.
        """
        self.stop_heartbeat()
        msg = {"type": "BYE", "executor_id": executor_id}
        # Never use the main socket: it sat idle for the whole run and a
        # NAT/conntrack-dropped connection swallows the send and stalls
        # receive() for the full 60s RPC timeout — longer than typical
        # monitor windows, so the "lost heartbeat" this method exists to
        # prevent would fire while BYE is stuck.  Fresh 5s dials only.
        ct, rt = self._effective_timeouts()
        for attempt in range(CONNECT_RETRIES):
            try:
                s = self._dial(connect_timeout=min(5.0, ct),
                               rpc_timeout=min(10.0, rt))
                try:
                    self.send(s, msg)
                    return self.receive(s)
                finally:
                    try:
                        s.close()
                    except OSError:
                        pass
            except ConnectionRefusedError:
                # Fast refusal = the server was stopped on purpose (normal
                # at teardown) — its monitor died with it, so BYE is moot.
                break
            except (ConnectionError, OSError):
                if attempt < CONNECT_RETRIES - 1:
                    time.sleep(0.5)
        return {"type": "OK"}  # server really gone (normal at teardown)

    def close(self):
        self.stop_heartbeat()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
