"""Per-executor node runtime (maps reference TFSparkNode.py:43-636).

`run/train/inference/shutdown` build closures that the cluster layer ships to
executors through a `Backend`.  Differences from the reference, by design
(SURVEY.md §7):

- No TF_CONFIG / port scouting.  Registration metadata feeds a
  **JAX-distributed bootstrap**: the sorted reservation list yields
  `(coordinator_addr, num_processes, process_id)`; `NodeContext.
  init_distributed()` hands these to `jax.distributed.initialize` on real
  multi-host TPU slices.  Chief (process 0) offers a coordinator port at
  registration time.
- Roles are `chief` / `worker` / `evaluator`.  Parameter servers have no TPU
  analog — async PS gradients are replaced by synchronous allreduce over
  ICI; `num_ps > 0` is accepted and scheduled as extra workers with a
  loud divergence warning (SURVEY.md §2.3).
- Data feeding is chunked (`marker.Chunk`) rather than per-record.
"""
import logging
from typing import Any, Callable, Dict, Optional
import multiprocessing as mp
import os
import time
import traceback
import uuid

from . import feed as feed_mod
from . import manager, marker, reservation, shm, tpu_info, util

logger = logging.getLogger(__name__)

CHUNK_SIZE = 512  # records per queue item when feeding


class DuplicateBootstrapError(RuntimeError):
    """A task retry tried to bootstrap an executor that already hosts a live
    node for this cluster_id (maps TFSparkNode.py:249-255).  Distinguished
    from other bootstrap failures because the ORIGINAL node is still alive:
    its heartbeat monitoring must not be cancelled on its behalf."""


class NodeContext:
    """Runtime context handed to the user's map_fun (maps TFSparkNode.py:59-99)."""

    def __init__(self, executor_id=0, job_name="chief", task_index=0, num_workers=1,
                 cluster_info=None, default_fs="file://", working_dir=None, mgr=None):
        self.executor_id = executor_id
        self.job_name = job_name
        self.task_index = task_index
        self.num_workers = num_workers
        self.cluster_info = cluster_info or []
        self.default_fs = default_fs
        self.working_dir = working_dir or os.getcwd()
        self.mgr = mgr
        self.user_name = os.environ.get("USER", "user")
        # process_id = rank in the sorted TRAINING node list (chief first).
        # Only chief+workers form the jax.distributed SPMD world — an
        # evaluator joining it would deadlock the gradient collectives (it
        # never enters the train step); like the reference's evaluator, it
        # runs outside the cluster's collective group (TFSparkNode.py:261).
        ordered = sorted(
            (n for n in self.cluster_info
             if n.get("job_name") in ("chief", "worker")),
            key=lambda n: (n.get("job_name") != "chief", n.get("executor_id", 0)))
        self.process_id = next(
            (i for i, n in enumerate(ordered)
             if n.get("executor_id") == executor_id), 0)
        self.num_processes = max(len(ordered), 1)
        chief = next((n for n in ordered if n.get("job_name") == "chief"), None)
        self.coordinator_address = None
        if chief is not None and chief.get("coordinator_port"):
            self.coordinator_address = f"{chief['host']}:{chief['coordinator_port']}"

    @property
    def is_chief(self):
        return self.job_name == "chief"

    def get_data_feed(self, train_mode=True, qname_in="input", qname_out="output",
                      input_mapping=None):
        """Build the DataFeed for InputMode.SPARK (maps TFNode.py:221-241)."""
        return feed_mod.DataFeed(self.mgr, train_mode, qname_in, qname_out, input_mapping)

    def absolute_path(self, path):
        """Normalize against the cluster default FS (maps TFNode.hdfs_path)."""
        return feed_mod.hdfs_path(self, path)

    def init_distributed(self):
        """Initialize jax.distributed from the reservation-derived identity.

        Call once per node process on real multi-host clusters BEFORE any
        other jax API.  No-op for single-process clusters (local testing) —
        where the full mesh is already visible to the one process.
        """
        if self.job_name not in ("chief", "worker"):
            logger.info("%s node runs outside the training SPMD world; "
                        "skipping jax.distributed init", self.job_name)
            return False
        if self.num_processes <= 1 or self.coordinator_address is None:
            logger.info("single-process cluster; skipping jax.distributed init")
            return False
        import jax
        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )
        return True


def _get_manager(cluster_info, host, executor_id):
    """Locate the queue manager for (host, executor_id) from the reservation
    list (maps TFSparkNode._get_manager, TFSparkNode.py:119-146)."""
    for node in cluster_info:
        if node["executor_id"] == executor_id and node["host"] == host:
            addr = tuple(node["addr"])
            mgr = manager.connect(addr, node["authkey"])
            logger.debug("connected to manager for executor %d, state=%s",
                         executor_id, manager.get_value(mgr, "state"))
            return mgr
    raise RuntimeError(
        f"no node registered for host={host} executor_id={executor_id}; "
        f"known: {[(n['host'], n['executor_id']) for n in cluster_info]}")


def _wrapper_fn(map_fun, tf_args, ctx):
    """Invoke the user function, re-injecting argv-style args
    (maps TFSparkNode.py:397-401)."""
    if isinstance(tf_args, list):
        import sys
        sys.argv = [sys.argv[0] if sys.argv else "map_fun"] + list(tf_args)
    return map_fun(tf_args, ctx)


def _heartbeat_interval(cluster_meta):
    """Beat-interval resolution.  The DRIVER decides whether heartbeats
    exist (heartbeat_timeout -> cluster_meta['heartbeat_interval'], 0 when
    the monitor is off): the monitor seeds every registered node into its
    beat table, so a node-side switch that disarmed beating while the
    monitor is armed would get every healthy node flagged dead.  The env
    var can therefore only retune the cadence, never disable it — and only
    downward: an override above the driver-computed base would beat wider
    than the monitor's window, which is functionally disabling."""
    base = float(cluster_meta.get("heartbeat_interval", 5.0))
    if base <= 0:
        return 0.0
    env = os.environ.get("TFOS_TPU_HEARTBEAT_INTERVAL")
    if env is not None:
        try:
            override = float(env)
        except ValueError:
            logger.warning("ignoring malformed TFOS_TPU_HEARTBEAT_INTERVAL=%r",
                           env)
            return base
        if override > 0:
            if override > base:
                logger.warning(
                    "TFOS_TPU_HEARTBEAT_INTERVAL=%s exceeds the monitor "
                    "window's cadence %.1fs; clamping", env, base)
            return min(override, base)
    return base


def _wrapper_fn_background(map_fun, tf_args, ctx, error_q_addr, authkey,
                           server_addr=None, hb_interval=5.0):
    """Background-process trampoline: exceptions land on the node's error
    queue instead of vanishing (maps TFSparkNode.py:403-409). This process
    is the liveness principal for the node, so it also owns the heartbeat:
    a silent death here (OOM, SIGKILL) is what the coordinator's monitor
    exists to catch."""
    from . import backend as backend_mod
    map_fun = backend_mod._loads_fn(map_fun)
    hb_client = None
    if server_addr is not None:
        # connect=False: the beat thread makes its own connections and
        # retries forever, so a briefly-unreachable server at node start
        # must not leave the node permanently unmonitored (the seeded
        # monitor would flag it dead).  The client exists even with
        # heartbeats disabled: BYE (normal-exit announcement) rides it, and
        # shutdown's wait-for-trainers ordering depends on BYE arriving.
        hb_client = reservation.Client(tuple(server_addr), connect=False)
        if hb_interval > 0:
            hb_client.start_heartbeat(ctx.executor_id, interval=hb_interval)
    mgr = None
    try:
        mgr = manager.connect(error_q_addr, authkey)
        ctx.mgr = mgr
        _wrapper_fn(map_fun, tf_args, ctx)
        if hb_client is not None:
            hb_client.bye(ctx.executor_id)
            hb_client.close()
    except BaseException:
        tb = traceback.format_exc()
        logger.error("background node fn failed:\n%s", tb)
        reported = False
        if mgr is not None:
            try:
                mgr.get_queue("error").put(tb)
                reported = True
            except Exception:
                pass
        if hb_client is not None:
            if reported:
                # BYE only once the death is durably REPORTED: the monitor
                # must not pile a spurious "heartbeat lost" on a reported
                # traceback — but if reporting failed, heartbeat loss is
                # the ONLY signal the driver will ever get; keep it.
                hb_client.bye(ctx.executor_id)
            else:
                resp = hb_client.report_error(
                    {"executor_id": ctx.executor_id}, tb)
                if resp is not None:  # None = report lost too; let the
                    hb_client.bye(ctx.executor_id)  # monitor flag the death
            hb_client.close()
        raise SystemExit(1)


def run(map_fun, tf_args, cluster_meta, tensorboard=False, log_dir=None,
        queues=("input", "output", "error", "control"), background=False):
    """Build the per-executor bootstrap closure (maps TFSparkNode.run,
    TFSparkNode.py:149-446).

    `cluster_meta` carries: cluster_id, cluster_template {job_name: [ids]},
    num_executors, default_fs, server_addr, num_chips (per worker),
    reservation_timeout.
    """

    def _mapfn(iterator):
        executor_id = None
        for item in iterator:
            executor_id = item
        assert executor_id is not None, "bootstrap task received no executor id"

        # 1. role assignment from the template (maps TFSparkNode.py:231-241)
        job_name, task_index = None, -1
        for jname, ids in cluster_meta["cluster_template"].items():
            if executor_id in ids:
                job_name = jname
                task_index = ids.index(executor_id)
                break
        assert job_name is not None, f"executor {executor_id} not in cluster template"
        logger.info("executor %d assigned %s:%d", executor_id, job_name, task_index)

        # Connect to the rendezvous server FIRST so that any bootstrap
        # failure below (duplicate-bootstrap, manager start, chip probe) is
        # reported to the driver instead of silently burning the full
        # reservation timeout.
        client = reservation.Client(cluster_meta["server_addr"])
        try:
            _bootstrap(executor_id, job_name, task_index, client, map_fun,
                       tf_args, cluster_meta, tensorboard, queues, background)
        except BaseException as e:
            resp = client.report_error(
                {"executor_id": executor_id, "job_name": job_name}, repr(e))
            if resp is not None and not isinstance(e, DuplicateBootstrapError):
                # Death is durably reported — suppress the monitor's
                # redundant "heartbeat lost" for this node.  If the report
                # was lost (resp None), heartbeat loss stays the only
                # signal the driver gets; keep it.  A duplicate-bootstrap
                # rejection must NOT send BYE: the ORIGINAL node on this
                # executor_id is alive and its heartbeats still matter.
                client.bye(executor_id)
            raise
        finally:
            client.close()

    return _mapfn


def _bootstrap(executor_id, job_name, task_index, client, map_fun, tf_args,
               cluster_meta, tensorboard, queues, background):
        # 2. stale-manager detection: a Spark task retry on the same executor
        #    must not double-start a node (maps TFSparkNode.py:249-255).
        state_file = os.path.join(os.getcwd(), ".tfos_cluster_id")
        if os.path.exists(state_file):
            with open(state_file) as f:
                prior = f.read().strip()
            if prior == str(cluster_meta["cluster_id"]):
                raise DuplicateBootstrapError(
                    f"executor {executor_id} already hosts a node for cluster "
                    f"{prior}; refusing duplicate bootstrap (task retry?)")
        with open(state_file, "w") as f:
            f.write(str(cluster_meta["cluster_id"]))

        # 4. queue manager: 'remote' for evaluator so the driver can reach its
        #    control queue (maps TFSparkNode.py:259-268).
        authkey = uuid.uuid4().bytes
        mode = "remote" if job_name == "evaluator" else "local"
        mgr = manager.start(authkey, list(queues), mode=mode)
        mgr.set("state", f"running/{job_name}")
        util.write_executor_id(executor_id)

        # 4b. shared-memory data plane: created BEFORE registration so any
        #     feeder that can discover this manager also finds the ring —
        #     both sides then use one transport for the whole feed (payload
        #     bytes ride /dev/shm; the queue carries ShmRefs + markers).
        if shm.ring_enabled():
            try:
                ring = shm.ShmChunkRing.create()
                mgr.set("shm_ring", ring.info())
                shm.advertise_file(ring.info())
                # Creator-side last-resort unlink.  atexit alone is not
                # enough: multiprocessing children exit via os._exit after
                # running only mp.util finalizers, so in an executor
                # process an atexit hook never fires (leaving the tracker
                # to warn about an already-unlinked segment).  Register
                # both — unlink is idempotent.
                import atexit
                from multiprocessing import util as mp_util
                atexit.register(ring.unlink)
                mp_util.Finalize(None, ring.unlink, exitpriority=10)
            except Exception:
                logger.warning("shm ring unavailable; data feed falls back "
                               "to manager-queue transport", exc_info=True)

        # 5. chief offers a jax.distributed coordinator port; every node
        #    learns it from the reservation list (replaces TF_CONFIG assembly,
        #    TFSparkNode.py:366-374).
        host = util.get_ip_address()
        coordinator_port = util.get_free_port(host) if job_name == "chief" else None

        # 6. optional profiler server (the TensorBoard-subprocess analog,
        #    TFSparkNode.py:282-319) — started lazily inside the user fn via
        #    utils.profiling; here we only reserve the port on the chief.
        tb_port = None
        if tensorboard and job_name == "chief":
            tb_port = int(os.environ.get("TFOS_TPU_PROFILER_PORT", 0)) or \
                util.get_free_port(host)

        # 7. register & rendezvous (maps TFSparkNode.py:321-360)
        node_meta = {
            "executor_id": executor_id,
            "host": host,
            "job_name": job_name,
            "task_index": task_index,
            "addr": list(mgr._tfos_addr),
            "authkey": authkey,
            "coordinator_port": coordinator_port,
            "tb_port": tb_port,
            "pid": os.getpid(),
        }
        client.register(node_meta)
        cluster_info = client.await_reservations(
            timeout=cluster_meta.get("reservation_timeout", 600))

        # TPU chip assignment (maps the cluster-aware second GPU pass,
        # TFSparkNode.py:376-378): only meaningful when several executors
        # share one TPU host; the worker index must be HOST-LOCAL (my rank
        # among same-host peers), which is only knowable post-rendezvous.
        # num_chips=0 means "whole host" (the common one-executor-per-host
        # layout) — no restriction applied.
        num_chips = cluster_meta.get("num_chips", 0)
        if num_chips:
            peers_here = sorted(n["executor_id"] for n in cluster_info
                                if n["host"] == host)
            local_index = peers_here.index(executor_id)
            tpu_info.assign_chips(num_chips, worker_index=local_index)

        num_workers = sum(len(v) for k, v in cluster_meta["cluster_template"].items()
                          if k in ("chief", "worker"))
        ctx = NodeContext(
            executor_id=executor_id,
            job_name=job_name,
            task_index=task_index,
            num_workers=num_workers,
            cluster_info=cluster_info,
            default_fs=cluster_meta.get("default_fs", "file://"),
            working_dir=os.getcwd(),
            mgr=mgr,
        )

        # 8. dispatch (maps TFSparkNode.py:397-443)
        try:
            if background:
                # SPARK input mode: node runs in a background process so this
                # task can return and free the executor slot for feeder tasks.
                ctx_bg = NodeContext(
                    executor_id=executor_id, job_name=job_name,
                    task_index=task_index, num_workers=num_workers,
                    cluster_info=cluster_info,
                    default_fs=cluster_meta.get("default_fs", "file://"),
                    working_dir=os.getcwd(), mgr=None)
                # map_fun crosses as a cloudpickle blob: a fn defined in a
                # __main__ script arrives here (executor) as a by-value
                # cloudpickle clone, which the standard pickler spawn uses
                # for Process args would refuse ("not the same object as
                # __main__.<fn>")
                from . import backend as backend_mod
                p = mp.Process(
                    target=_wrapper_fn_background,
                    args=(backend_mod._dumps_fn(map_fun), tf_args, ctx_bg,
                          mgr._tfos_addr, authkey,
                          cluster_meta.get("server_addr"),
                          _heartbeat_interval(cluster_meta)),
                    name=f"node-{job_name}-{task_index}")
                p.start()
                logger.info("started background node process pid=%d", p.pid)
            else:
                # foreground node: this process is the liveness principal
                hb_interval = _heartbeat_interval(cluster_meta)
                if hb_interval > 0:
                    client.start_heartbeat(executor_id, interval=hb_interval)
                _wrapper_fn(map_fun, tf_args, ctx)
                client.bye(executor_id)
        except BaseException:
            tb = traceback.format_exc()
            logger.error("node fn failed on executor %d:\n%s", executor_id, tb)
            try:
                mgr.get_queue("error").put(tb)
            except Exception:
                pass
            raise  # _mapfn's outer handler reports to the server, then BYEs


def _push_chunks(q, iterator, mgr=None, timeout=600.0, equeue=None,
                 progress_fn=None, progress_every=512, poll_cb=None):
    """Push records as chunk batches; returns the record count.  Shared by
    the train and inference feeders — inference's 1:1 result accounting
    depends on this count being exact.

    Transport: when the node advertises a shared-memory ring
    (`shm.discover`), chunk payloads are copied into the ring and the
    queue carries tiny `shm.ShmRef` handles — the SURVEY.md §7
    "process-boundary feed throughput" fix.  Packed sub-chunks coalesce
    into ~TFOS_TPU_CHUNK_BYTES payloads first, because each queue
    operation costs a manager round trip and per-item overhead (not
    bandwidth) dominates once bytes ride shared memory.  Without a ring,
    uniform numeric chunks go through the queue as columnar PackedChunks
    (round-1 behavior, still the fallback when rings cannot be created)."""
    ring = None
    if mgr is not None and shm.ring_enabled():
        try:
            info = shm.discover(mgr)
            if info:
                ring = shm.attach_cached(info)
        except Exception:
            logger.warning("could not attach shm ring; using queue "
                           "transport", exc_info=True)
    target_bytes = int(os.environ.get("TFOS_TPU_CHUNK_BYTES", 8 << 20))
    if ring is not None:
        target_bytes = min(target_bytes, ring.capacity_bytes // 4)

    pending = []        # packed sub-chunks awaiting one coalesced write
    pending_bytes = 0

    last_poll = [time.time()]

    def _maybe_poll():
        # progress reports must flow DURING the push too: under ring
        # backpressure the feeder spends the whole epoch here, and a
        # crash would otherwise find an empty high-water map
        if poll_cb is not None and time.time() - last_poll[0] >= 0.5:
            last_poll[0] = time.time()
            try:
                poll_cb()
            except Exception:
                logger.warning("progress poll failed", exc_info=True)

    def _abort_on_error():
        # polled while a ring write blocks on a full ring: a dead/failed
        # consumer should surface its error, not a generic RingTimeout
        # (maps the reference's error polling during queue.join(),
        # TFSparkNode.py:488-495)
        _maybe_poll()
        tb = _peek_error(equeue) if equeue is not None else None
        if tb is not None:
            raise RuntimeError(f"training function failed:\n{tb}")

    def _flush():
        nonlocal pending, pending_bytes, ring
        if not pending:
            return
        subs, pending, pending_bytes = pending, [], 0
        try:
            parts, n = (shm.encode_multi(subs) if len(subs) > 1
                        else shm.encode_chunk(subs[0]))
        except Exception:
            # codec surprise: the queue still works (ring untouched)
            logger.warning("chunk encode failed; chunks ride the queue",
                           exc_info=True)
        else:
            try:
                ref = ring.write(parts, n, timeout=timeout,
                                 should_abort=_abort_on_error)
            except (shm.RingTimeout, RuntimeError):
                raise
            except Exception:
                # write() repaired its frame state, but a transport that
                # failed generically once is not worth retrying — drop to
                # queue transport for the remainder of this task
                logger.warning("ring write failed; disabling ring for this "
                               "task", exc_info=True)
                ring = None
            else:
                # q.put stays OUTSIDE the handler: a manager failure after
                # a successful write must fail the task (its frames are
                # committed; re-sending the subs via the queue would both
                # duplicate records and orphan the FULL frames)
                q.put(ref)
                return
        for sub in subs:
            q.put(sub)

    def _send(records):
        nonlocal pending_bytes
        packed = marker.pack_records(records)
        if ring is None:
            q.put(packed)
            return
        if isinstance(packed, marker.PackedChunk):
            nb = sum(c.nbytes for c in packed.columns)
            if nb > ring.capacity_bytes - (1 << 16):
                # larger than the ring itself: this one rides the queue
                _flush()
                q.put(packed)
                return
            # flush BEFORE the payload would cross the target (the 64 KiB
            # margin covers codec metadata), so each ring write stays
            # within its intended frame budget instead of spilling into
            # an extra mostly-empty slot
            if pending and pending_bytes + nb > target_bytes - (1 << 16):
                _flush()
            pending.append(packed)
            pending_bytes += nb
            if len(pending) >= 64:
                _flush()
        else:
            # object records: size unknowable without pickling; ship the
            # coalesced buffer right away
            pending.append(packed)
            _flush()

    count = 0
    last_mark = 0
    chunk = []
    for item in iterator:
        chunk.append(item)
        # a due progress marker cuts the chunk early: markers must land
        # every ~progress_every records even when that is smaller than
        # the transport chunk
        marker_due = (progress_fn is not None
                      and count + len(chunk) - last_mark >= progress_every)
        if len(chunk) >= CHUNK_SIZE or marker_due:
            _send(chunk)
            count += len(chunk)
            chunk = []
            _maybe_poll()
            if marker_due:
                # records must be IN the queue before the marker claims
                # them (a marker racing ahead of its chunk would confirm
                # consumption of records still in the pending buffer)
                _flush()
                q.put(progress_fn(count))
                last_mark = count
    if chunk:
        _send(chunk)
        count += len(chunk)
    _flush()
    if progress_fn is not None and count > last_mark:
        q.put(progress_fn(count))
    return count


PROGRESS_HEADER = "__tfos_pid__"


def train(cluster_info: Any, cluster_meta: Any, feed_timeout: float = 600,
          qname: str = "input",
          skip_offsets: Optional[Dict[int, int]] = None,
          track_progress: bool = False,
          progress_every: int = 512) -> Callable:
    """Build the feeder closure for training data (maps TFSparkNode.train,
    TFSparkNode.py:448-515).

    ``track_progress`` (feed-offset resume, net-new): each partition's
    first record is a ``(PROGRESS_HEADER, pid)`` tag (cluster.train adds
    it); the feeder strips it, skips the first ``skip_offsets[pid]``
    records (already consumed by a previous attempt), interleaves
    consumption-confirmed `marker.Progress` checkpoints every
    ``progress_every`` records, and forwards the high-water marks to the
    driver's reservation server — both while feeding and while waiting
    for consumption — so `cluster.run_elastic` can bound duplicate
    delivery on relaunch to ~one progress window.
    """
    import itertools

    def _train(iterator):
        mgr = _get_manager(cluster_info, util.get_ip_address(), util.read_executor_id())
        state = manager.get_value(mgr, "state") or ""
        if "terminating" in state:
            # Late partitions are skipped fast once training asked to stop
            # (maps TFSparkNode.py:470-476).
            logger.info("node is terminating; skipping partition")
            count = sum(1 for _ in iterator)
            logger.info("skipped %d records", count)
            # Signal the driver that remaining feeding is pointless
            # (maps TFSparkNode.py:499-511).
            try:
                client = reservation.Client(cluster_meta["server_addr"])
                client.request_stop()
                client.close()
            except Exception:
                pass
            return

        q = mgr.get_queue(qname)
        equeue = mgr.get_queue("error")
        progress_fn = poll_cb = None
        client = None
        skip = 0
        if track_progress:
            head = next(iterator, None)
            if not (isinstance(head, tuple) and len(head) == 2
                    and head[0] == PROGRESS_HEADER):
                raise RuntimeError(
                    "track_progress feeder got an untagged partition "
                    "(cluster.train tags partitions when tracking)")
            pid = int(head[1])
            skip = int((skip_offsets or {}).get(pid, 0))
            if skip:
                logger.info("partition %d: skipping %d already-consumed "
                            "records (feed-offset resume)", pid, skip)
                consumed = sum(1 for _ in itertools.islice(iterator, skip))
                skip = consumed      # short partition: skip what exists
            progress_fn = lambda n: marker.Progress(pid, skip + n)  # noqa
            client = reservation.Client(cluster_meta["server_addr"],
                                        connect=False)
            last_sent = {}

            def poll_cb():
                got = manager.get_value(mgr, "feed_progress") or {}
                fresh = {p: o for p, o in got.items()
                         if o > last_sent.get(p, 0)}
                if fresh:
                    client.send_progress(fresh)
                    last_sent.update(fresh)

        count = _push_chunks(q, iterator, mgr=mgr, timeout=feed_timeout,
                             equeue=equeue, progress_fn=progress_fn,
                             progress_every=progress_every, poll_cb=poll_cb)
        logger.info("pushed %d records into %s queue", count, qname)

        _join_with_watchdog(q, equeue, feed_timeout, poll_cb=poll_cb)
        if client is not None:
            # join means every item was DEQUEUED, not that every record
            # was handed to the training fn (drained-but-unreturned
            # segments exist) — so the final report forwards the
            # consumer's own delivered-confirmed kv value, never
            # skip+count; an unconfirmed tail is re-fed next attempt
            # (bounded by one progress window)
            try:
                poll_cb()
            except Exception:
                logger.warning("final progress poll failed", exc_info=True)
            client.close()

    return _train


def inference(cluster_info: Any, cluster_meta: Any,
              qname: str = "input") -> Callable:
    """Build the feeder/collector closure for inference (maps
    TFSparkNode.inference, TFSparkNode.py:518-579).  Returns exactly one
    result per input record, per partition."""

    def _inference(iterator):
        mgr = _get_manager(cluster_info, util.get_ip_address(), util.read_executor_id())
        q = mgr.get_queue(qname)
        equeue = mgr.get_queue("error")
        count = _push_chunks(q, iterator, mgr=mgr, equeue=equeue)
        q.put(marker.EndPartition())
        logger.info("pushed %d records (+EndPartition) into %s queue", count, qname)
        if count == 0:
            return iter([])

        _join_with_watchdog(q, equeue, timeout=600)

        # Drain exactly `count` results (maps TFSparkNode.py:567-577).
        out = mgr.get_queue("output")
        results = []
        while len(results) < count:
            results.append(out.get())
            out.task_done()
        logger.info("collected %d inference results", len(results))
        return iter(results)

    return _inference


def _peek_error(equeue):
    """Return the first queued error traceback without consuming it
    (get/task_done then re-put, the reference's peek/re-put trick that
    keeps the error visible to the shutdown path too,
    TFSparkNode.py:624-630), or None when the queue is empty."""
    if equeue.empty():
        return None
    tb = equeue.get()
    equeue.task_done()
    equeue.put(tb)
    return tb


def _join_with_watchdog(q, equeue, timeout, poll_cb=None):
    """queue.join() with error propagation + feed timeout (maps
    TFSparkNode.py:485-495).  ``poll_cb`` (feed-offset resume) runs every
    poll tick — most consumption happens while the feeder waits here, so
    this is where high-water marks actually reach the driver."""
    import threading

    joined = threading.Event()

    def _join():
        q.join()
        joined.set()

    t = threading.Thread(target=_join, daemon=True)
    t.start()
    deadline = time.time() + timeout
    while not joined.is_set():
        tb = _peek_error(equeue)
        if tb is not None:
            raise RuntimeError(f"training function failed:\n{tb}")
        if time.time() > deadline:
            raise TimeoutError(
                f"data feed not consumed within {timeout}s — the training "
                f"process is likely dead or stuck")
        if poll_cb is not None:
            try:
                poll_cb()
            except Exception:
                logger.warning("progress poll failed", exc_info=True)
        joined.wait(0.5)


def shutdown(cluster_info: Any, queues: Any = ("input",),
             grace_secs: float = 0) -> Callable:
    """Build the per-executor shutdown closure (maps TFSparkNode.shutdown,
    TFSparkNode.py:582-636): push end-of-feed sentinels, wait out the grace
    period (chief may still be exporting), surface late errors, mark stopped."""

    def _shutdown(iterator):
        for _ in iterator:
            pass
        mgr = _get_manager(cluster_info, util.get_ip_address(), util.read_executor_id())
        for qname in queues:
            try:
                mgr.get_queue(qname).put(None)
            except Exception:
                logger.warning("could not push sentinel into %s", qname)
        if grace_secs:
            time.sleep(grace_secs)
        # Late-error surfacing (maps TFSparkNode.py:624-630): leave the
        # error visible for other shutdown paths while still raising here.
        late_error = _peek_error(mgr.get_queue("error"))
        # The ring name is removed here (mappings survive on POSIX, so a
        # consumer still draining is unaffected; the creator's atexit
        # unlink is then a no-op).
        try:
            info = shm.discover(mgr)
            if info:
                shm.ShmChunkRing.unlink_by_name(info["name"])
            shm.remove_advertisement()
        except Exception:
            pass
        # Marking 'stopped' is the manager's death warrant: the executor's
        # bootstrap process waits for this state, then stops the manager and
        # exits (backend._bootstrap_trampoline) — the node process gets its
        # full grace window first.
        mgr.set("state", "stopped")
        if late_error is not None:
            raise RuntimeError(f"node failed after feeding completed:\n{late_error}")

    return _shutdown
