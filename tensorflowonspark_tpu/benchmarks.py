"""Shared benchmark definitions: chip peaks and the flagship-LM config.

Single source of truth for the driver metric (bench.py) and the repro
harness (scripts/bench_lm.py) so the two cannot drift — the recorded
numbers in BASELINE.md are only comparable if every harness builds the
exact same step.
"""

# bf16 matmul peaks by device_kind substring (public spec sheet numbers)
PEAK_BF16 = {
    "TPU v5 lite": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6": 918e12,
}


def bf16_peak(device_kind):
    """Peak bf16 FLOP/s for a jax device_kind string, or None if unknown —
    callers must NOT silently substitute a default: an MFU percent against
    the wrong peak is a fabricated number."""
    return next((v for k, v in PEAK_BF16.items() if k in device_kind), None)


# The round-3 flagship-LM benchmark config (BASELINE.md round 3): 0.87B
# params, the north-star workload class on one chip.  Frozen — changing any
# value invalidates vs_baseline comparability and requires a BASELINE.md
# methodology note.
FLAGSHIP_LM = dict(
    vocab_size=32000, d_model=2048, n_heads=16, n_kv_heads=8,
    n_layers=16, d_ff=8192, max_seq_len=1024, dtype="bfloat16",
    rope=True, attention_impl="auto")
# Round-5 re-baseline (BASELINE.md round 5): same dims, RMSNorm — the
# config this framework RECOMMENDS for new decoder-only models since
# round 3 (the frozen v1 kept LayerNorm only for comparability; the
# round-4 verdict called the freeze stale).  v1 stays measured in aux
# for one transition round, exactly like the round-3 metric change.
FLAGSHIP_LM_V2 = dict(FLAGSHIP_LM, norm_type="rmsnorm")
FLAGSHIP_BATCH = 8
FLAGSHIP_MU_DTYPE = "bfloat16"
# Round-6 headline optimizer: the single-pass fused AdamW kernel
# (ops/fused_optim.py) — same math as optax adamw(mu_dtype=bfloat16), one
# HBM pass over grad/param/moments instead of the optax chain's several.
# The optax reference stays measurable via make_flagship_step(
# optimizer="adamw") and bench.py's transition aux row.
FLAGSHIP_OPTIMIZER = "adamw_fused"
ROUND1_LM_MFU = 47.0  # BASELINE.md round-1 flagship-LM row (vs_baseline denom)

# The decode_ms segment workload (bench.py --segments): steady-state
# paged slot decode on the flagship dims, sized for the gather path's
# worst case — long max_seq, rows only partially filled — where the
# flash-decode kernel's per-row length bound pays most.  Frozen like
# FLAGSHIP_LM: changing any value invalidates decode_ms comparability.
FLAGSHIP_DECODE = dict(n_slots=16, page_size=64, max_seq=4096, fill=2000)


def make_decode_step(impl="kernel", n_slots=None, page_size=None,
                     max_seq=None, fill=None, quantize=None):
    """Build the steady-state paged slot-decode step for the decode_ms
    segment: flagship-LM dims (FLAGSHIP_LM_V2) at ``max_seq``, every row
    fully page-mapped and pre-filled to ``fill`` tokens, so each timed
    step is one mid-stream decode token for all ``n_slots`` rows.
    ``impl`` picks the paged READ path ("kernel" = the Pallas
    flash-decode kernel, "einsum" = the full-gather reference —
    TransformerConfig.paged_attn_impl).  ``quantize`` ("int8"/"int4")
    stores the weights quantized exactly as serving does (quantize_tree
    then the compute-width cast for the survivors, serve._load_lm's
    order), so the step decodes through the fused-dequant quant_matmul
    path.  Returns
    ``(step, params, cache, (toks, temps, seeds, ords))``; the cache is
    donated — advance with
    ``toks, cache, ords = step(params, cache, toks, temps, seeds, ords)``.
    The kv content is untrained garbage (zeros): decode cost is
    shape/length-bound, not value-bound, so timing is unaffected."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import decode as decode_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_DECODE
    n_slots = n_slots or d["n_slots"]
    page = page_size or d["page_size"]
    max_seq = max_seq or d["max_seq"]
    fill = d["fill"] if fill is None else fill
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    # params don't depend on seq length: init with a short trace
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    if quantize:
        from tensorflowonspark_tpu import quantize as quantize_mod
        params = quantize_mod.quantize_tree(params, mode=quantize)
        params = quantize_mod.cast_float_leaves(params, cfg.dtype)
    from tensorflowonspark_tpu.serve import max_table_pages
    max_pages = max_table_pages(max_seq, page)
    # every row fully mapped (pages are row-contiguous; +1 = the sink,
    # unused here but init_paged_slot_cache's caller contract): steps
    # can never write past an allocated page, and the KERNEL's work is
    # still bounded by `fill` (its per-row length bound), while the
    # einsum body gathers the whole max_seq view — the contrast the
    # segment measures
    slot_model, cache = decode_mod.init_paged_slot_cache(
        model, n_slots, page, n_slots * max_pages + 1,
        paged_attn_impl=impl)
    set_table = decode_mod._jitted_set_row_page_table(slot_model)
    for row in range(n_slots):
        entries = jnp.arange(row * max_pages, (row + 1) * max_pages,
                             dtype=jnp.int32)
        cache = set_table(cache, jnp.asarray(row, jnp.int32), entries)

    def _fill_leaf(path, leaf):
        if decode_mod._leaf_name(path) in ("cache_index", "pos_index"):
            return jnp.full(leaf.shape, fill, jnp.int32)
        return leaf

    cache = jax.tree_util.tree_map_with_path(_fill_leaf, cache)
    step = decode_mod._jitted_slot_step(slot_model)
    toks = jnp.zeros((n_slots,), jnp.int32)
    temps = jnp.zeros((n_slots,), jnp.float32)   # greedy
    seeds = jnp.zeros((n_slots,), jnp.int32)
    ords = jnp.zeros((n_slots,), jnp.int32)
    return step, params, cache, (toks, temps, seeds, ords)


# The qmm_ms segment workload (bench.py --segments): one decode-shaped
# weight matmul on the flagship's widest projection — d_model -> d_ff
# (2048 x 8192, the DenseMLP up-projection kernel) with a decode batch
# of rows.  Decode matmuls are weight-read-bound (rows is the slot
# batch, tiny next to the kernel), so the fused-dequant stores' smaller
# resident bytes (qmm_weight_bytes) should convert ~directly into step
# time.  Frozen like FLAGSHIP_DECODE: changing any value invalidates
# qmm_ms comparability.
FLAGSHIP_QMM = dict(rows=16, in_dim=2048, out_dim=8192, group_size=128)


def make_qmm_op(mode="bf16", rows=None, in_dim=None, out_dim=None,
                group_size=None):
    """Build the qmm_ms segment op: a jitted ``fn(x, w) -> y`` plus its
    ``(x, w)`` operands for one flagship projection matmul.  ``mode``
    picks the weight store — "bf16" = the dense compute-width matmul
    (the W16 serving baseline), "int8" / "int4" = the fused-dequant
    Pallas kernels (ops.quant_matmul) over the quantized leaf, built by
    the same quantize_tree serving uses.  The activation is bf16 in
    every mode: weight-only quantization (W8A16 / W4A16)."""
    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import quantize as quantize_mod
    from tensorflowonspark_tpu.ops import quant_matmul

    d = FLAGSHIP_QMM
    rows = rows or d["rows"]
    K = in_dim or d["in_dim"]
    N = out_dim or d["out_dim"]
    G = group_size or d["group_size"]
    kx, kw = jax.random.split(jax.random.key(0))
    x = jax.random.normal(kx, (rows, K), jnp.bfloat16)
    w = jax.random.normal(kw, (K, N), jnp.float32)
    if mode == "bf16":
        return jax.jit(jnp.dot), x, w.astype(jnp.bfloat16)
    qleaf = quantize_mod.quantize_tree(
        {"proj": {"kernel": w}}, mode=mode, min_elements=0,
        group_size=G)["proj"]["kernel"]
    return jax.jit(quant_matmul), x, qleaf


def qmm_weight_bytes(mode, in_dim=None, out_dim=None, group_size=None):
    """Analytic resident weight bytes for one qmm_ms matmul — the
    per-step weight read the segment exists to price (a decode matmul
    streams the whole kernel once per step).  bf16: K·N·2.  int8: K·N
    payload + N per-channel f32 scales.  int4: the nibble-packed
    payload (two input rows per stored byte, input dim padded to whole
    groups) + one f32 scale per (group, output channel)."""
    d = FLAGSHIP_QMM
    K = in_dim or d["in_dim"]
    N = out_dim or d["out_dim"]
    G = group_size or d["group_size"]
    if mode == "bf16":
        return K * N * 2
    if mode == "int8":
        return K * N + N * 4
    if mode == "int4":
        n_groups = -(-K // G)
        return n_groups * (G // 2) * N + n_groups * N * 4
    raise ValueError(f"unknown qmm mode {mode!r}")


# The prefill_ms segment workload (bench.py --segments): steady-state
# batched multi-row prefill into a paged pool — every row already
# holding `fill` tokens of context, each timed dispatch pushing one
# more `chunk`-wide slab for ALL rows through _jitted_slot_prefill_many.
# `fill` is deliberately NOT page-aligned (matching FLAGSHIP_DECODE's)
# so the steady state exercises the page-straddling chunk path.  The
# contrast is the paged S>1 WRITE discipline ("kernel" = the Pallas
# paged-prefill flash kernel writing W = chunk//page + 1 pages per row
# in place, "blend" = the one-hot einsum blend that materializes the
# ENTIRE pool every chunk — TransformerConfig.paged_prefill_impl).
# Frozen like FLAGSHIP_DECODE: changing any value invalidates
# prefill_ms comparability.
FLAGSHIP_PREFILL_KERNEL = dict(n_slots=4, page_size=64, max_seq=4096,
                               fill=2000, chunk=256)


def make_prefill_chunk_step(impl="kernel", n_slots=None, page_size=None,
                            max_seq=None, fill=None, chunk=None):
    """Build the steady-state paged prefill chunk step for the
    prefill_ms segment: flagship-LM dims (FLAGSHIP_LM_V2) at
    ``max_seq``, every row fully page-mapped, each dispatch prefilling
    the same ``chunk``-wide slab at offset ``fill`` for all ``n_slots``
    rows at once.  Re-dispatch is idempotent — the row indices are SET
    to ``fill + chunk`` (not accumulated) and the same pages are
    rewritten — so timing loops just rebind the donated cache.
    ``impl`` picks the paged S>1 prefill path ("kernel" = the Pallas
    in-place page-write kernel, "blend" = the full-pool einsum blend —
    TransformerConfig.paged_prefill_impl).  Returns
    ``(prefill, params, cache, (chunks, rows, starts, n_valids, sink))``;
    advance with ``logits, cache = prefill(params, cache, *args)``.
    The kv content is untrained garbage: prefill cost is shape-bound,
    not value-bound, so timing is unaffected."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models import decode as decode_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_PREFILL_KERNEL
    n_slots = n_slots or d["n_slots"]
    page = page_size or d["page_size"]
    max_seq = max_seq or d["max_seq"]
    fill = d["fill"] if fill is None else fill
    chunk = chunk or d["chunk"]
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    # params don't depend on seq length: init with a short trace
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    from tensorflowonspark_tpu.serve import max_table_pages
    max_pages = max_table_pages(max_seq, page)
    n_pages = n_slots * max_pages + 1       # +1 = the sink page
    slot_model, cache = decode_mod.init_paged_slot_cache(
        model, n_slots, page, n_pages, paged_prefill_impl=impl)
    set_table = decode_mod._jitted_set_row_page_table(slot_model)
    for row in range(n_slots):
        entries = jnp.arange(row * max_pages, (row + 1) * max_pages,
                             dtype=jnp.int32)
        cache = set_table(cache, jnp.asarray(row, jnp.int32), entries)
    prefill = decode_mod._jitted_slot_prefill_many(slot_model)
    rs = np.random.RandomState(0)
    chunks = jnp.asarray(rs.randint(1, cfg.vocab_size, (n_slots, chunk)),
                         jnp.int32)
    rows = jnp.arange(n_slots, dtype=jnp.int32)
    starts = jnp.full((n_slots,), fill, jnp.int32)
    n_valids = jnp.full((n_slots,), chunk, jnp.int32)
    sink = jnp.asarray(n_pages - 1, jnp.int32)
    return prefill, params, cache, (chunks, rows, starts, n_valids, sink)


def prefill_chunk_write_bytes(impl, n_slots=None, page_size=None,
                              max_seq=None, chunk=None):
    """Analytic KV-pool WRITE traffic per prefill_ms dispatch (all
    layers, k + v, bf16 pool): the blend path materializes a full new
    pool every chunk — every page, occupied or not — while the kernel
    writes only the W = chunk//page + 1 pages each row's chunk can
    touch, in place.  The segment reports both so the
    traffic-scales-with-chunk claim is a number in the JSON, not
    prose."""
    d = FLAGSHIP_PREFILL_KERNEL
    n_slots = n_slots or d["n_slots"]
    page = page_size or d["page_size"]
    max_seq = max_seq or d["max_seq"]
    chunk = chunk or d["chunk"]
    n_kv = FLAGSHIP_LM_V2["n_kv_heads"]
    dh = FLAGSHIP_LM_V2["d_model"] // FLAGSHIP_LM_V2["n_heads"]
    page_bytes = page * n_kv * dh * 2       # bf16 kv pool
    if impl == "blend":
        from tensorflowonspark_tpu.serve import max_table_pages
        pages = n_slots * max_table_pages(max_seq, page) + 1   # WHOLE pool
    else:
        pages = n_slots * (chunk // page + 1)     # W pages/row, in place
    return FLAGSHIP_LM_V2["n_layers"] * 2 * pages * page_bytes


# The ttft_ms segment workload (bench.py --segments): a burst of queued
# prompts admitted through the continuous batcher's prefill engine —
# time-to-first-token with batched multi-row prefill (prefill_rows=4)
# vs the sequential admission baseline (prefill_rows=1).  Dense slot
# cache: the segment isolates admission batching, not page residency.
# Frozen like FLAGSHIP_LM: changing any value invalidates ttft_ms
# comparability.
FLAGSHIP_PREFILL = dict(n_slots=8, prompts=8, prompt_len=768, max_new=2,
                        prefill_chunk=256, prefill_rows=4, max_seq=1024)


def make_prefill_burst(prefill_rows=None, n_slots=None, prompts=None,
                       prompt_len=None, max_new=None, prefill_chunk=None,
                       max_seq=None):
    """Build the ttft_ms segment workload: a ContinuousBatcher on the
    flagship-LM dims (FLAGSHIP_LM_V2 at ``max_seq``) plus the burst of
    distinct random prompts to submit.  Returns
    ``(batcher, prompts_list, max_new)``; the caller submits the burst,
    drains every handle, and reads TTFT from ``batcher.stats()``
    (ttft_ms_sum / ttft_count deltas).  Caller must ``batcher.stop()``.
    Prompt content is random garbage: prefill cost is shape-bound, not
    value-bound, so timing is unaffected; prompts are DISTINCT so the
    prefix cache cannot short-circuit the work being measured."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import serve as serve_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_PREFILL
    rows = d["prefill_rows"] if prefill_rows is None else prefill_rows
    n_slots = n_slots or d["n_slots"]
    n_prompts = prompts or d["prompts"]
    prompt_len = prompt_len or d["prompt_len"]
    max_new = max_new or d["max_new"]
    chunk = prefill_chunk or d["prefill_chunk"]
    max_seq = max_seq or d["max_seq"]
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    # params don't depend on seq length: init with a short trace
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    batcher = serve_mod.ContinuousBatcher(
        model, params, n_slots=n_slots, read_chunk=4, prefill_chunk=chunk,
        prefill_rows=rows)
    rs = np.random.RandomState(0)
    prompts_list = [rs.randint(1, cfg.vocab_size,
                               prompt_len).astype("int32").tolist()
                    for _ in range(n_prompts)]
    return batcher, prompts_list, max_new


# The engine_tps segment workload (bench.py --segments): sustained decode
# through the FULL ContinuousBatcher — admission, dispatch, readback,
# stream delivery — not a bare step microbench.  Short prompts + long
# generations so steady-state decode dominates and the segment measures
# the engine's host/device overlap (async double-buffered loop vs the
# serialized baseline), the exact path decode_ms cannot see.  Frozen like
# FLAGSHIP_PREFILL: changing any value invalidates engine_tps
# comparability.
FLAGSHIP_ENGINE = dict(n_slots=8, prompts=16, prompt_len=64, max_new=96,
                       prefill_chunk=256, prefill_rows=4, max_seq=256)


def make_engine_burst(engine="async", n_slots=None, prompts=None,
                      prompt_len=None, max_new=None, prefill_chunk=None,
                      prefill_rows=None, max_seq=None, pipeline_depth=2,
                      quantize=None):
    """Build the engine_tps segment workload: a ContinuousBatcher on the
    flagship-LM dims running the requested ``engine`` ("async" = the
    double-buffered producer/consumer pipeline, "serial" = the
    single-thread dispatch/process baseline) plus the prompt burst to
    submit.  ``quantize`` ("int8"/"int4") stores the weights quantized
    exactly as serving does (serve._load_lm's quantize-then-cast order),
    so the whole burst decodes through the fused-dequant quant_matmul
    path.  Returns ``(batcher, prompts_list, max_new)``; the caller
    submits the burst, drains every handle, and computes tokens/s from
    wall clock (device-idle fraction comes from ``batcher.stats()``).
    Caller must ``batcher.stop()``.  Prompts are distinct random garbage
    for the same reasons as :func:`make_prefill_burst`."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import serve as serve_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_ENGINE
    n_slots = n_slots or d["n_slots"]
    n_prompts = prompts or d["prompts"]
    prompt_len = prompt_len or d["prompt_len"]
    max_new = max_new or d["max_new"]
    chunk = prefill_chunk or d["prefill_chunk"]
    rows = d["prefill_rows"] if prefill_rows is None else prefill_rows
    max_seq = max_seq or d["max_seq"]
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    if quantize:
        from tensorflowonspark_tpu import quantize as quantize_mod
        params = quantize_mod.quantize_tree(params, mode=quantize)
        params = quantize_mod.cast_float_leaves(params, cfg.dtype)
    batcher = serve_mod.ContinuousBatcher(
        model, params, n_slots=n_slots, read_chunk=4, prefill_chunk=chunk,
        prefill_rows=rows, engine=engine, pipeline_depth=pipeline_depth)
    rs = np.random.RandomState(0)
    prompts_list = [rs.randint(1, cfg.vocab_size,
                               prompt_len).astype("int32").tolist()
                    for _ in range(n_prompts)]
    return batcher, prompts_list, max_new


# The spec_tps segment workload (bench.py --segments): sustained decode
# through the ContinuousBatcher with speculation in each of its modes —
# "ngram" (model-free prompt-lookup drafting), "model" (a 4-layer
# scaled-down draft LM on the flagship dims), "off" (the plain-step
# baseline the other two are compared against).  Prompts are REPETITIVE
# (a short random motif tiled to prompt_len): prompt-lookup speculation
# pays off exactly when the continuation echoes the context, so this
# workload is where ngram drafting must beat spec-off — the acceptance
# rate and adaptive mean-k ride along as aux.  Greedy requests: the
# accept rate then measures draft quality alone, not sampling noise.
# Frozen like FLAGSHIP_ENGINE: changing any value invalidates spec_tps
# comparability.
FLAGSHIP_SPEC = dict(n_slots=8, prompts=16, prompt_len=64, max_new=96,
                     prefill_chunk=256, prefill_rows=4, max_seq=256,
                     draft_k=4, motif_len=8, draft_layers=4)


def make_spec_burst(mode="ngram", n_slots=None, prompts=None,
                    prompt_len=None, max_new=None, prefill_chunk=None,
                    prefill_rows=None, max_seq=None, draft_k=None):
    """Build the spec_tps segment workload: a ContinuousBatcher on the
    flagship-LM dims with ``mode`` speculation ("ngram" / "model" /
    "off") plus the repetitive prompt burst to submit.  Returns
    ``(batcher, prompts_list, max_new)``; the caller submits the burst
    greedily, drains every handle, computes tokens/s from wall clock,
    and reads acceptance/mean-k aux from ``batcher.stats()``.  Caller
    must ``batcher.stop()``."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import serve as serve_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_SPEC
    n_slots = n_slots or d["n_slots"]
    n_prompts = prompts or d["prompts"]
    prompt_len = prompt_len or d["prompt_len"]
    max_new = max_new or d["max_new"]
    chunk = prefill_chunk or d["prefill_chunk"]
    rows = d["prefill_rows"] if prefill_rows is None else prefill_rows
    max_seq = max_seq or d["max_seq"]
    draft_k = draft_k or d["draft_k"]
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    draft_model = draft_params = None
    if mode == "model":
        d_cfg = TransformerConfig(**dict(
            FLAGSHIP_LM_V2, max_seq_len=max_seq,
            n_layers=d["draft_layers"]))
        draft_model = Transformer(d_cfg)
        draft_params = draft_model.init(
            jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]
    batcher = serve_mod.ContinuousBatcher(
        model, params, n_slots=n_slots, read_chunk=4, prefill_chunk=chunk,
        prefill_rows=rows, spec_draft=mode, draft_model=draft_model,
        draft_params=draft_params, draft_k=draft_k)
    rs = np.random.RandomState(0)
    motif_len = d["motif_len"]
    prompts_list = []
    for _ in range(n_prompts):
        motif = rs.randint(1, cfg.vocab_size, motif_len)
        reps = prompt_len // motif_len + 1
        prompts_list.append(
            np.tile(motif, reps)[:prompt_len].astype("int32").tolist())
    return batcher, prompts_list, max_new


# The migrate_ms segment workload (bench.py --segments): one live paged
# session frozen mid-decode on a source ContinuousBatcher, shipped page-
# by-page through a real kvtransfer.PageServer socket on localhost, and
# resumed on a destination batcher — the disaggregated-serving handoff
# end to end (freeze gather, wire framing, page upload, table splice).
# Long prompt so the snapshot carries a realistic page count; the
# decode keeps running through the cut, so the segment can also report
# the client-visible stream stall.  Frozen like FLAGSHIP_ENGINE:
# changing any value invalidates migrate_ms comparability.
FLAGSHIP_MIGRATE = dict(n_slots=4, prompt_len=192, max_new=48,
                        prefill_chunk=256, kv_page_size=32, kv_pages=64,
                        max_seq=256)


def make_migrate_pair(n_slots=None, prompt_len=None, max_new=None,
                      prefill_chunk=None, kv_page_size=None,
                      kv_pages=None, max_seq=None):
    """Build the migrate_ms segment workload: source and destination
    ContinuousBatchers on the flagship-LM dims (both paged — migration
    ships occupied pages) plus the prompt to move.  Returns
    ``(src, dst, prompt, max_new)``; the caller submits to ``src``,
    freezes mid-decode, wires the snapshot across, resumes on ``dst``,
    and times the handoff.  Caller must stop BOTH batchers.  Prompt
    content is random garbage for the same reasons as
    :func:`make_prefill_burst`."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import serve as serve_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_MIGRATE
    n_slots = n_slots or d["n_slots"]
    prompt_len = prompt_len or d["prompt_len"]
    max_new = max_new or d["max_new"]
    chunk = prefill_chunk or d["prefill_chunk"]
    page = kv_page_size or d["kv_page_size"]
    pages = kv_pages or d["kv_pages"]
    max_seq = max_seq or d["max_seq"]
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    def mk():
        return serve_mod.ContinuousBatcher(
            model, params, n_slots=n_slots, read_chunk=1,
            prefill_chunk=chunk, kv_page_size=page, kv_pages=pages)

    src, dst = mk(), mk()
    rs = np.random.RandomState(0)
    prompt = rs.randint(1, cfg.vocab_size,
                        prompt_len).astype("int32").tolist()
    return src, dst, prompt, max_new


# The sched_ms segment workload (bench.py --segments): a paged batcher
# saturated by long batch-class sessions while short interactive
# requests arrive on top — the mixed-priority contention story the
# preemption controller exists for.  With preemption on, interactive
# pressure parks the longest-remaining batch session (freeze → host-side
# snapshot → resume when pressure drops); the segment reports interactive
# p95 queueing delay with the controller on vs off.  Paged KV so parking
# exercises the real page-pool accounting.  Frozen like FLAGSHIP_ENGINE:
# changing any value invalidates sched_ms comparability.
FLAGSHIP_SCHED = dict(n_slots=4, batch_sessions=4, batch_prompt_len=64,
                      batch_max_new=96, inter_sessions=8,
                      inter_prompt_len=32, inter_max_new=4,
                      prefill_chunk=256, kv_page_size=32, kv_pages=64,
                      max_seq=256, preempt_ms=5.0)


def make_sched_burst(preempt=True, n_slots=None, prefill_chunk=None,
                     kv_page_size=None, kv_pages=None, max_seq=None,
                     preempt_ms=None):
    """Build the sched_ms segment workload: one paged ContinuousBatcher
    (preemption controller armed when ``preempt``) plus the two prompt
    populations.  Returns ``(batcher, batch_prompts, batch_max_new,
    inter_prompts, inter_max_new)``; the caller saturates the slots with
    the batch population, trickles the interactive one on top, drains
    everything, and reads per-class queueing delay from
    ``batcher.stats()``.  Caller must ``batcher.stop()``.  Prompts are
    distinct random garbage for the same reasons as
    :func:`make_prefill_burst`."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import serve as serve_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_SCHED
    n_slots = n_slots or d["n_slots"]
    chunk = prefill_chunk or d["prefill_chunk"]
    page = kv_page_size or d["kv_page_size"]
    pages = kv_pages or d["kv_pages"]
    max_seq = max_seq or d["max_seq"]
    preempt_ms = d["preempt_ms"] if preempt_ms is None else preempt_ms
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    batcher = serve_mod.ContinuousBatcher(
        model, params, n_slots=n_slots, read_chunk=1,
        prefill_chunk=chunk, kv_page_size=page, kv_pages=pages,
        preempt_ms=preempt_ms if preempt else 0.0,
        park_capacity=d["batch_sessions"])
    rs = np.random.RandomState(0)

    def burst(n, length):
        return [rs.randint(1, cfg.vocab_size,
                           length).astype("int32").tolist()
                for _ in range(n)]

    batch_prompts = burst(d["batch_sessions"], d["batch_prompt_len"])
    inter_prompts = burst(d["inter_sessions"], d["inter_prompt_len"])
    return (batcher, batch_prompts, d["batch_max_new"],
            inter_prompts, d["inter_max_new"])


# The warm_ttft_ms segment workload (bench.py --segments): 8 returning
# conversations against a paged batcher with the host-DRAM page tier
# armed.  Cold pass prefills every prompt from scratch and retires, so
# each conversation's full-prefix pages demote to the host tier; the
# device prefix cache is then evicted so the warm pass can ONLY be
# served by host->device promotion.  The segment reports mean TTFT for
# the warm pass vs the cold pass — the cross-turn prefill-skip win the
# hierarchical kv cache exists for.  Long prompts (6 full 32-token
# pages) so the skipped prefill dominates TTFT.  Frozen like
# FLAGSHIP_ENGINE: changing any value invalidates warm_ttft_ms
# comparability.
FLAGSHIP_WARM = dict(n_slots=4, conversations=8, prompt_len=192,
                     max_new=8, prefill_chunk=256, kv_page_size=32,
                     kv_pages=96, host_cache_mb=256, max_seq=256)


def make_warm_burst(n_slots=None, conversations=None, prompt_len=None,
                    max_new=None, prefill_chunk=None, kv_page_size=None,
                    kv_pages=None, host_cache_mb=None, max_seq=None):
    """Build the warm_ttft_ms segment workload: one paged
    ContinuousBatcher with the host tier armed, plus the conversation
    prompts.  Returns ``(batcher, prompts_list, max_new)``; the caller
    runs the burst cold (timing per-request TTFT), flushes the tier,
    evicts the device prefix cache, re-runs the SAME burst warm, and
    compares.  Caller must ``batcher.stop()``.  Prompts are distinct
    random garbage for the same reasons as :func:`make_prefill_burst` —
    prefix reuse here is exact-key, so garbage reuses as well as text."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import serve as serve_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_WARM
    n_slots = n_slots or d["n_slots"]
    n_conv = conversations or d["conversations"]
    prompt_len = prompt_len or d["prompt_len"]
    max_new = max_new or d["max_new"]
    chunk = prefill_chunk or d["prefill_chunk"]
    page = kv_page_size or d["kv_page_size"]
    pages = kv_pages or d["kv_pages"]
    cache_mb = host_cache_mb or d["host_cache_mb"]
    max_seq = max_seq or d["max_seq"]
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    batcher = serve_mod.ContinuousBatcher(
        model, params, n_slots=n_slots, read_chunk=1,
        prefill_chunk=chunk, kv_page_size=page, kv_pages=pages,
        host_cache_mb=cache_mb)
    rs = np.random.RandomState(0)
    prompts_list = [rs.randint(1, cfg.vocab_size,
                               prompt_len).astype("int32").tolist()
                    for _ in range(n_conv)]
    return batcher, prompts_list, max_new


# The job_tps segment workload (bench.py --segments): an offline bulk-
# inference job (jobs.JobManager — the TFoS data pump) draining a jsonl
# record file through a paged ContinuousBatcher as batch-class work,
# while a trickle of interactive requests rides on top.  The segment
# reports sustained records/s at full engine utilization plus the
# interactive p95 latency with the job running vs idle — the WFQ story
# at fleet scale: batch jobs soak every spare slot, interactive latency
# holds.  Preemption armed (same controller FLAGSHIP_SCHED prices).
# Frozen like FLAGSHIP_ENGINE: changing any value invalidates job_tps
# comparability.
FLAGSHIP_JOB = dict(n_slots=4, records=64, record_prompt_len=32,
                    record_max_new=4, partitions=4, workers=3,
                    checkpoint_every=16, inter_probes=8,
                    inter_prompt_len=32, inter_max_new=4,
                    prefill_chunk=256, kv_page_size=32, kv_pages=64,
                    max_seq=256, preempt_ms=5.0)


def make_job_burst(n_slots=None, records=None, record_prompt_len=None,
                   prefill_chunk=None, kv_page_size=None, kv_pages=None,
                   max_seq=None, preempt_ms=None):
    """Build the job_tps segment workload: one paged ContinuousBatcher
    (preemption armed) plus the two prompt populations.  Returns
    ``(batcher, record_prompts, record_max_new, inter_prompts,
    inter_max_new)``; the caller spools ``record_prompts`` into a jsonl
    input file, runs a real :class:`jobs.JobManager` over it with a
    dispatch callable driving THIS batcher, and probes interactive
    latency while the job drains.  Caller must ``batcher.stop()``.
    Prompts are distinct random garbage for the same reasons as
    :func:`make_prefill_burst`."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import serve as serve_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_JOB
    n_slots = n_slots or d["n_slots"]
    records = records or d["records"]
    rec_len = record_prompt_len or d["record_prompt_len"]
    chunk = prefill_chunk or d["prefill_chunk"]
    page = kv_page_size or d["kv_page_size"]
    pages = kv_pages or d["kv_pages"]
    max_seq = max_seq or d["max_seq"]
    preempt_ms = d["preempt_ms"] if preempt_ms is None else preempt_ms
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    batcher = serve_mod.ContinuousBatcher(
        model, params, n_slots=n_slots, read_chunk=1,
        prefill_chunk=chunk, kv_page_size=page, kv_pages=pages,
        preempt_ms=preempt_ms)
    rs = np.random.RandomState(0)

    def burst(n, length):
        return [rs.randint(1, cfg.vocab_size,
                           length).astype("int32").tolist()
                for _ in range(n)]

    record_prompts = burst(records, rec_len)
    inter_prompts = burst(d["inter_probes"], d["inter_prompt_len"])
    return (batcher, record_prompts, d["record_max_new"],
            inter_prompts, d["inter_max_new"])


# The long_ttft_ms segment workload (bench.py --segments): one 32k-token
# mega-prompt streamed through the long-context admission lane while a
# short interactive burst rides on top.  Armed, the prompt admits
# immediately but prefills chunk-by-chunk under the lane's per-round
# quota (pages allocated per chunk, the page table growing from its
# 8-entry seed as the stream advances, cold prefix pages demoted to the
# host tier when the pool runs dry); disarmed, the same prompt is a
# normal admission that reserves its full page run up front and hogs
# the prefill budget.  The segment reports mega-prompt TTFT plus the
# interactive p95 queueing delay both ways — the lane's story is the
# interactive p95 holding while the monster streams.  The pool is sized
# a hair over the mega-prompt's own run so the interactive burst's
# retired prefix pages MUST be reclaimed through the overflow valve.
# Frozen like FLAGSHIP_ENGINE: changing any value invalidates
# long_ttft_ms comparability.
FLAGSHIP_LONG = dict(n_slots=4, long_prompt_len=32768, long_max_new=8,
                     long_prompt_threshold=4096, inter_sessions=8,
                     inter_prompt_len=32, inter_max_new=4,
                     prefill_chunk=256, kv_page_size=32, kv_pages=1040,
                     host_cache_mb=64, max_seq=32800)


def make_long_burst(armed=True, n_slots=None, long_prompt_len=None,
                    prefill_chunk=None, kv_page_size=None, kv_pages=None,
                    host_cache_mb=None, max_seq=None,
                    long_prompt_threshold=None):
    """Build the long_ttft_ms segment workload: one paged
    ContinuousBatcher (mega-prompt lane armed when ``armed`` — disarmed
    = threshold 0, the prompt admits as ordinary work) plus the
    mega-prompt and the interactive population.  Returns ``(batcher,
    long_prompt, long_max_new, inter_prompts, inter_max_new)``; the
    caller submits the mega-prompt, trickles the interactive burst on
    top, drains everything, and reads TTFT / per-class queueing delay /
    growth and demotion counters from ``batcher.stats()``.  Caller must
    ``batcher.stop()``.  Prompts are distinct random garbage for the
    same reasons as :func:`make_prefill_burst`."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu import serve as serve_mod
    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig)

    d = FLAGSHIP_LONG
    n_slots = n_slots or d["n_slots"]
    long_len = long_prompt_len or d["long_prompt_len"]
    chunk = prefill_chunk or d["prefill_chunk"]
    page = kv_page_size or d["kv_page_size"]
    pages = kv_pages or d["kv_pages"]
    cache_mb = host_cache_mb or d["host_cache_mb"]
    max_seq = max_seq or d["max_seq"]
    threshold = (long_prompt_threshold or d["long_prompt_threshold"]
                 if armed else 0)
    cfg = TransformerConfig(**dict(FLAGSHIP_LM_V2, max_seq_len=max_seq))
    model = Transformer(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    batcher = serve_mod.ContinuousBatcher(
        model, params, n_slots=n_slots, read_chunk=1,
        prefill_chunk=chunk, kv_page_size=page, kv_pages=pages,
        host_cache_mb=cache_mb, long_prompt_threshold=threshold)
    rs = np.random.RandomState(0)

    def burst(n, length):
        return [rs.randint(1, cfg.vocab_size,
                           length).astype("int32").tolist()
                for _ in range(n)]

    long_prompt = burst(1, long_len)[0]
    inter_prompts = burst(d["inter_sessions"], d["inter_prompt_len"])
    return (batcher, long_prompt, d["long_max_new"],
            inter_prompts, d["inter_max_new"])


def make_flagship_step(batch_size=None, seq_len=None, config="v2",
                       optimizer=None):
    """Build the flagship-LM training step exactly as the driver metric
    runs it: returns (step, state, tokens, n_params).  Donated state —
    call as ``state, m = step(state, tokens, rng)``.
    ``config``: "v2" (rmsnorm, the round-5 headline) or "v1" (the frozen
    round-3 layernorm config, kept for the transition round's aux row).
    ``optimizer``: None -> FLAGSHIP_OPTIMIZER (adamw_fused, the round-6
    headline); "adamw" -> the optax reference (transition aux row);
    "sgd0" -> zero-lr momentum-less SGD, the near-free update whose step
    time isolates the optimizer segment (bench.py's opt_ms)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflowonspark_tpu.models.transformer import (
        Transformer, TransformerConfig, lm_loss)
    from tensorflowonspark_tpu.optim import make_optimizer
    from tensorflowonspark_tpu.parallel import train as train_mod

    cfg_kw = dict(FLAGSHIP_LM_V2 if config == "v2" else FLAGSHIP_LM)
    if seq_len:
        cfg_kw["max_seq_len"] = seq_len
    B = batch_size or FLAGSHIP_BATCH
    S = cfg_kw["max_seq_len"]
    cfg = TransformerConfig(**cfg_kw)
    model = Transformer(cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (B, S + 1)),
        jnp.int32)
    params = model.init(jax.random.key(0), tokens[:, :S])["params"]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))

    def loss_fn(p, batch, rng):
        return lm_loss(model.apply({"params": p}, batch[:, :-1]),
                       batch[:, 1:])

    name = optimizer or FLAGSHIP_OPTIMIZER
    if name == "sgd0":
        # momentum=None (not 0.0): optax.sgd keeps a full trace state for
        # any non-None momentum, which would put optimizer bandwidth back
        # into the "no optimizer" segment baseline
        opt, _ = make_optimizer("sgd", learning_rate=0.0, momentum=None)
    else:
        opt, _ = make_optimizer(name, learning_rate=3e-4,
                                mu_dtype=FLAGSHIP_MU_DTYPE)
    state = train_mod.create_train_state(params, opt)
    step = train_mod.make_train_step(loss_fn, opt, donate=True)
    return step, state, tokens, n_params
