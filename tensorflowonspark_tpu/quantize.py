"""Weight-only int8 post-training quantization for serving.

Serving on TPU is usually HBM-bandwidth-bound: each request reads every
weight once.  Storing kernels as int8 with per-output-channel float32
scales cuts that traffic (and the export artifact) ~4x, while activations
stay in the model's compute dtype (W8A16).  Under jit the dequantize
(`q.astype(dtype) * scale`) fuses into the consuming matmul's operand
read, so the full-precision kernel never materializes in HBM.

    qtree = quantize.quantize_tree(params)         # kernels -> {q, scale}
    logits = model.apply({"params": quantize.dequantize_tree(qtree)}, x)

The quantized tree is a plain pytree (int8/float32 arrays), so
`utils.checkpoint`, `export`, and host<->device transfer all handle it
unchanged.  Quantization is symmetric per-channel (no zero-points): TPU
matmuls take the scale as a single fused multiply.
"""
import logging
import re

logger = logging.getLogger(__name__)

DEFAULT_TARGETS = r"kernel$"
_QKEYS = frozenset({"q", "scale"})


def _is_qleaf(node):
    # the int8 dtype requirement disambiguates from a real param dict that
    # happens to use the key names "q" and "scale" (float leaves)
    return (isinstance(node, dict) and set(node) == _QKEYS
            and str(getattr(node.get("q"), "dtype", "")) == "int8")


def quantize_tree(params, targets=DEFAULT_TARGETS, min_elements=4096,
                  axis=-1):
    """Replace every matching >=2-D kernel with {"q": int8, "scale": f32}.

    `scale` is per-slice along `axis` (the output-channel axis for
    [in, out] kernels); small tensors (< `min_elements`) and non-matches
    pass through unquantized.  Returns a tree with the same nesting —
    quantized leaves become 2-key dicts that `dequantize_tree` recognizes.
    """
    import jax.numpy as jnp

    from .treeutil import flatten_with_paths

    pat = re.compile(targets)
    flat, _ = flatten_with_paths(params)
    selected = {
        path for path, leaf in flat.items()
        if (hasattr(leaf, "ndim") and leaf.ndim >= 2
            and pat.search(path) and leaf.size >= min_elements
            and jnp.issubdtype(leaf.dtype, jnp.floating))}
    n_quant = [0]

    def walk(node, path):
        if isinstance(node, dict) and not _is_qleaf(node):
            return {k: walk(v, f"{path}/{k}" if path else k)
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            if hasattr(node, "_fields"):   # namedtuple: flatten names
                return type(node)(*[      # fields GetAttrKey-style (".f")
                    walk(v, f"{path}/.{f}" if path else f".{f}")
                    for f, v in zip(node._fields, node)])
            return type(node)(
                [walk(v, f"{path}/{i}" if path else str(i))
                 for i, v in enumerate(node)])
        leaf = node
        if path in selected:
            w = jnp.asarray(leaf, jnp.float32)
            reduce_axes = tuple(i for i in range(w.ndim)
                                if i != (axis % w.ndim))
            amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
            scale = jnp.maximum(amax, 1e-12) / 127.0
            q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
            n_quant[0] += 1
            return {"q": q, "scale": scale.astype(jnp.float32)}
        return leaf

    out = walk(params, "")
    if not n_quant[0]:
        raise ValueError(f"no kernels matched targets={targets!r} with "
                         f">= {min_elements} elements")
    if n_quant[0] != len(selected):
        # flatten_with_paths saw leaves the dict/list walk couldn't reach
        # (e.g. a custom pytree node) — fail loudly rather than silently
        # leaving matched kernels unquantized
        raise ValueError(
            f"selected {len(selected)} kernels but quantized {n_quant[0]}; "
            "the param tree contains containers quantize_tree cannot "
            "rewrite (only dict/list/tuple nesting is supported — convert "
            "with e.g. flax.core.unfreeze first)")
    qb, fb = quantized_bytes(out)
    logger.info("quantized %d kernels to int8 (weight bytes %.2fx smaller)",
                n_quant[0], fb / max(qb, 1))
    return out


def dequantize_tree(qtree, dtype=None):
    """Rebuild a model-ready param tree; quantized leaves become
    `q.astype(dtype) * scale` (XLA fuses this into the consumer when
    called under jit).  `dtype=None` keeps float32."""
    import jax.numpy as jnp

    target = jnp.float32 if dtype is None else jnp.dtype(dtype)

    def walk(node):
        if _is_qleaf(node):
            return (node["q"].astype(jnp.float32)
                    * node["scale"]).astype(target)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            walked = [walk(v) for v in node]
            return (type(node)(*walked) if hasattr(node, "_fields")
                    else type(node)(walked))
        return node

    return walk(qtree)


def cast_float_leaves(tree, dtype):
    """Cast floating leaves to `dtype`, SKIPPING quantized leaves — their
    int8 payload is already narrow and their f32 scales must stay f32 (a
    blanket cast would round the scales to the compute width).  The
    serving load path uses this to store unquantized leaves (embeddings,
    norm scales) at the model's compute width.  A tree_map with the
    qleaf dicts as leaves, so any registered pytree container (FrozenDict,
    custom nodes) traverses like the plain-dict case."""
    import jax
    import jax.numpy as jnp

    target = jnp.dtype(dtype)

    def cast(x):
        if _is_qleaf(x):
            return x
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(target)
        return x

    return jax.tree_util.tree_map(cast, tree, is_leaf=_is_qleaf)


def quantized_bytes(qtree):
    """(quantized_bytes, float_equivalent_bytes) over quantized leaves."""
    qb = fb = 0

    def walk(node):
        nonlocal qb, fb
        if _is_qleaf(node):
            qb += node["q"].size + node["scale"].size * 4
            fb += node["q"].size * 4
        elif isinstance(node, dict):
            for v in node.values():
                walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v)

    walk(qtree)
    return qb, fb


def max_abs_error(params, qtree):
    """Worst-case per-tensor |W - dequant(Q)| (quantization noise bound:
    0.5 * scale per channel)."""
    import jax.numpy as jnp

    deq = dequantize_tree(qtree)
    worst = 0.0

    def walk(a, b):
        nonlocal worst
        if isinstance(a, dict):
            for k in a:
                walk(a[k], b[k])
        elif isinstance(a, (list, tuple)):
            for x, y in zip(a, b):
                walk(x, y)
        else:
            worst = max(worst, float(jnp.max(jnp.abs(
                jnp.asarray(a, jnp.float32) - jnp.asarray(b, jnp.float32)))))

    walk(params, deq)
    return worst
