"""Weight-only int8/int4 post-training quantization for serving.

Serving on TPU is usually HBM-bandwidth-bound: each request reads every
weight once.  Storing kernels as int8 with per-output-channel float32
scales cuts that traffic (and the export artifact) ~4x, while activations
stay in the model's compute dtype (W8A16).  Int4 halves the weight bytes
again (W4A16) with per-group symmetric scales (AWQ-style: a ``group_size``
run of input rows shares one scale per output column), two nibbles packed
per int8 byte.

Two consumption paths exist for a quantized tree:

  * materialized: ``dequantize_tree`` rebuilds float kernels (XLA fuses
    the ``q.astype(dtype) * scale`` into the consuming matmul when jitted
    — hopefully; there is no guarantee the dense kernel never spills).
  * fused: ``models.transformer.QuantDense`` consumes int8 dicts and
    ``Int4Weight`` leaves directly, routing through the
    ``ops.quant_matmul`` Pallas kernel which dequantizes weight tiles in
    VMEM so the dense kernel never exists in HBM.  ``qdense_view``
    prepares a param tree for that path.

    qtree = quantize.quantize_tree(params)         # kernels -> {q, scale}
    logits = model.apply({"params": quantize.dequantize_tree(qtree)}, x)

The int8 tree is a plain pytree (int8/float32 arrays), so
`utils.checkpoint`, `export`, and host<->device transfer all handle it
unchanged.  ``Int4Weight`` is a registered pytree node created at load
time (it is not a checkpoint format: export artifacts stay f32/int8 and
int4 packing happens in ``serve._load_lm``).  Quantization is symmetric
(no zero-points): TPU matmuls take the scale as a single fused multiply.
"""
import logging
import re

logger = logging.getLogger(__name__)

DEFAULT_TARGETS = r"kernel$"
DEFAULT_GROUP_SIZE = 128
_QKEYS = frozenset({"q", "scale"})
_INT4_REGISTERED = [False]


def _is_qleaf(node):
    # the int8 dtype requirement disambiguates from a real param dict that
    # happens to use the key names "q" and "scale" (float leaves)
    return (isinstance(node, dict) and set(node) == _QKEYS
            and str(getattr(node.get("q"), "dtype", "")) == "int8")


class Int4Weight:
    """A nibble-packed int4 kernel leaf: ``q`` holds two signed 4-bit
    values per int8 byte along the input dim (row ``2i`` in the low
    nibble, ``2i+1`` in the high nibble), ``scale`` is float32 with one
    row per ``group_size`` input rows, one column per output channel.
    ``in_dim`` records the unpadded input dim (packing zero-pads to a
    whole number of groups).  Registered as a jax pytree node on first
    construction, so it rides through jit/device_put like any array
    pair; ``in_dim``/``group_size`` are static aux data."""

    __slots__ = ("q", "scale", "in_dim", "group_size")

    def __init__(self, q, scale, in_dim, group_size):
        _register_int4()
        self.q = q
        self.scale = scale
        self.in_dim = int(in_dim)
        self.group_size = int(group_size)

    @property
    def out_dim(self):
        return self.q.shape[-1]

    def __repr__(self):
        return (f"Int4Weight(in_dim={self.in_dim}, out_dim={self.out_dim}, "
                f"group_size={self.group_size})")


def _register_int4():
    if _INT4_REGISTERED[0]:
        return
    import jax

    def flatten(w):
        return (w.q, w.scale), (w.in_dim, w.group_size)

    def unflatten(aux, children):
        out = object.__new__(Int4Weight)
        out.q, out.scale = children
        out.in_dim, out.group_size = aux
        return out

    jax.tree_util.register_pytree_node(Int4Weight, flatten, unflatten)
    _INT4_REGISTERED[0] = True


def is_quantized_leaf(node):
    """True for either quantized-leaf form: an int8 {"q", "scale"} dict
    or an Int4Weight."""
    return _is_qleaf(node) or isinstance(node, Int4Weight)


def int4_pack(w, group_size=DEFAULT_GROUP_SIZE):
    """Quantize a 2-D [in, out] float kernel to a nibble-packed
    Int4Weight with per-(group, output-channel) symmetric scales.

    ``group_size`` must be even; the input dim is zero-padded up to a
    whole number of groups before packing, so ``q`` has exactly
    ``n_groups * group_size / 2`` rows and ``scale`` has ``n_groups``.
    Values are clipped to the symmetric int4 range [-7, 7] (the -8 code
    is unused, matching the int8 path's +-127 symmetry).
    """
    import jax.numpy as jnp

    if group_size < 2 or group_size % 2:
        raise ValueError(f"group_size must be even and >= 2, "
                         f"got {group_size}")
    w = jnp.asarray(w, jnp.float32)
    if w.ndim != 2:
        raise ValueError(f"int4_pack needs a 2-D [in, out] kernel, "
                         f"got shape {w.shape}")
    in_dim, out_dim = w.shape
    n_groups = -(-in_dim // group_size)
    padded = n_groups * group_size
    if padded != in_dim:
        w = jnp.pad(w, ((0, padded - in_dim), (0, 0)))
    grouped = w.reshape(n_groups, group_size, out_dim)
    amax = jnp.max(jnp.abs(grouped), axis=1)              # [G, out]
    scale = jnp.maximum(amax, 1e-12) / 7.0
    q = jnp.clip(jnp.round(grouped / scale[:, None, :]), -7, 7)
    q = q.reshape(padded, out_dim).astype(jnp.int8)
    lo, hi = q[0::2], q[1::2]
    packed = ((lo & jnp.int8(0x0F)) | (hi << 4)).astype(jnp.int8)
    return Int4Weight(packed, scale.astype(jnp.float32), in_dim, group_size)


def int4_unpack(w):
    """Rebuild the float32 [in, out] kernel from an Int4Weight (padding
    rows sliced off).  The exact dequant the fused kernel computes."""
    import jax.numpy as jnp

    p = w.q
    # arithmetic shifts on int8 sign-extend the nibbles
    lo = ((p << 4) >> 4).astype(jnp.float32)
    hi = (p >> 4).astype(jnp.float32)
    rows = jnp.stack([lo, hi], axis=1).reshape(2 * p.shape[0], p.shape[1])
    scales = jnp.repeat(w.scale, w.group_size, axis=0)
    return (rows * scales)[: w.in_dim]


def quantize_tree(params, targets=DEFAULT_TARGETS, min_elements=4096,
                  axis=-1, mode="int8", group_size=DEFAULT_GROUP_SIZE):
    """Replace every matching >=2-D kernel with a quantized leaf.

    ``mode="int8"``: leaves become ``{"q": int8, "scale": f32}`` with
    `scale` per-slice along `axis` (the output-channel axis for
    [in, out] kernels).  ``mode="int4"``: 2-D kernels become nibble-packed
    ``Int4Weight`` leaves with per-``group_size`` scales; matched kernels
    of rank >= 3 (e.g. stacked MoE expert banks consumed by raw einsums)
    fall back to int8 dicts so the whole tree stays servable.  Small
    tensors (< `min_elements`) and non-matches pass through unquantized.
    Returns a tree with the same nesting that `dequantize_tree`
    recognizes.
    """
    import jax.numpy as jnp

    from .treeutil import flatten_with_paths

    if mode not in ("int8", "int4"):
        raise ValueError(f"mode must be 'int8' or 'int4', got {mode!r}")
    if mode == "int4" and axis not in (-1, 1):
        raise ValueError("int4 grouping runs along the input dim; only "
                         "axis=-1 output-channel scales are supported")
    pat = re.compile(targets)
    flat, _ = flatten_with_paths(params)
    selected = {
        path for path, leaf in flat.items()
        if (hasattr(leaf, "ndim") and leaf.ndim >= 2
            and pat.search(path) and leaf.size >= min_elements
            and jnp.issubdtype(leaf.dtype, jnp.floating))}
    n_quant = [0]

    def quantize_int8(leaf):
        w = jnp.asarray(leaf, jnp.float32)
        reduce_axes = tuple(i for i in range(w.ndim)
                            if i != (axis % w.ndim))
        amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        return {"q": q, "scale": scale.astype(jnp.float32)}

    def walk(node, path):
        if isinstance(node, dict) and not _is_qleaf(node):
            return {k: walk(v, f"{path}/{k}" if path else k)
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            if hasattr(node, "_fields"):   # namedtuple: flatten names
                return type(node)(*[      # fields GetAttrKey-style (".f")
                    walk(v, f"{path}/.{f}" if path else f".{f}")
                    for f, v in zip(node._fields, node)])
            return type(node)(
                [walk(v, f"{path}/{i}" if path else str(i))
                 for i, v in enumerate(node)])
        leaf = node
        if path in selected:
            n_quant[0] += 1
            if mode == "int4" and leaf.ndim == 2:
                return int4_pack(leaf, group_size)
            return quantize_int8(leaf)
        return leaf

    out = walk(params, "")
    if not n_quant[0]:
        raise ValueError(f"no kernels matched targets={targets!r} with "
                         f">= {min_elements} elements")
    if n_quant[0] != len(selected):
        # flatten_with_paths saw leaves the dict/list walk couldn't reach
        # (e.g. a custom pytree node) — fail loudly rather than silently
        # leaving matched kernels unquantized
        raise ValueError(
            f"selected {len(selected)} kernels but quantized {n_quant[0]}; "
            "the param tree contains containers quantize_tree cannot "
            "rewrite (only dict/list/tuple nesting is supported — convert "
            "with e.g. flax.core.unfreeze first)")
    qb, fb = quantized_bytes(out)
    logger.info("quantized %d kernels to %s (weight bytes %.2fx smaller)",
                n_quant[0], mode, fb / max(qb, 1))
    return out


def dequantize_leaf(node, dtype=None):
    """Dequantize a single quantized leaf (int8 dict or Int4Weight) to a
    float array; `dtype=None` keeps float32."""
    import jax.numpy as jnp

    target = jnp.float32 if dtype is None else jnp.dtype(dtype)
    if _is_qleaf(node):
        return (node["q"].astype(jnp.float32) * node["scale"]).astype(target)
    if isinstance(node, Int4Weight):
        return int4_unpack(node).astype(target)
    raise TypeError(f"not a quantized leaf: {type(node)!r}")


def dequantize_tree(qtree, dtype=None):
    """Rebuild a model-ready param tree; quantized leaves become
    `q.astype(dtype) * scale` (XLA fuses this into the consumer when
    called under jit).  `dtype=None` keeps float32."""

    def walk(node):
        if is_quantized_leaf(node):
            return dequantize_leaf(node, dtype)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            walked = [walk(v) for v in node]
            return (type(node)(*walked) if hasattr(node, "_fields")
                    else type(node)(walked))
        return node

    return walk(qtree)


def qdense_view(qtree):
    """Prepare a quantized tree for the fused QuantDense path: 2-D
    quantized leaves (int8 dicts and Int4Weight) pass through for the
    kernel to consume in quantized form; rank->=3 int8 leaves (stacked
    expert banks read by raw einsums, which QuantDense never sees)
    dequantize to float32 here.  Float leaves are untouched."""

    def walk(node):
        if isinstance(node, Int4Weight):
            return node
        if _is_qleaf(node):
            return node if node["q"].ndim == 2 else dequantize_leaf(node)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            walked = [walk(v) for v in node]
            return (type(node)(*walked) if hasattr(node, "_fields")
                    else type(node)(walked))
        return node

    return walk(qtree)


def cast_float_leaves(tree, dtype):
    """Cast floating leaves to `dtype`, SKIPPING quantized leaves — their
    int8/int4 payload is already narrow and their f32 scales must stay
    f32 (a blanket cast would round the scales to the compute width).
    The serving load path uses this to store unquantized leaves
    (embeddings, norm scales) at the model's compute width.  A tree_map
    with the quantized leaves as leaves, so any registered pytree
    container (FrozenDict, custom nodes) traverses like the plain-dict
    case."""
    import jax
    import jax.numpy as jnp

    target = jnp.dtype(dtype)

    def cast(x):
        if is_quantized_leaf(x):
            return x
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(target)
        return x

    return jax.tree_util.tree_map(cast, tree, is_leaf=is_quantized_leaf)


def quantized_bytes(qtree):
    """(quantized_bytes, float_equivalent_bytes) over quantized leaves."""
    qb = fb = 0

    def walk(node):
        nonlocal qb, fb
        if isinstance(node, Int4Weight):
            qb += node.q.size + node.scale.size * 4
            fb += node.in_dim * node.out_dim * 4
        elif _is_qleaf(node):
            qb += node["q"].size + node["scale"].size * 4
            fb += node["q"].size * 4
        elif isinstance(node, dict):
            for v in node.values():
                walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v)

    walk(qtree)
    return qb, fb


def max_abs_error(params, qtree):
    """Worst-case per-tensor |W - dequant(Q)| (quantization noise bound:
    0.5 * scale per channel/group)."""
    import jax.numpy as jnp

    deq = dequantize_tree(qtree)
    worst = 0.0

    def walk(a, b):
        nonlocal worst
        if isinstance(a, dict):
            for k in a:
                walk(a[k], b[k])
        elif isinstance(a, (list, tuple)):
            for x, y in zip(a, b):
                walk(x, y)
        else:
            worst = max(worst, float(jnp.max(jnp.abs(
                jnp.asarray(a, jnp.float32) - jnp.asarray(b, jnp.float32)))))

    walk(params, deq)
    return worst
