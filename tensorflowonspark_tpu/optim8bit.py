"""8-bit blockwise-quantized Adam state (bitsandbytes-style, TPU-native).

At LM scale the Adam states dominate training memory: for the 0.87B
flagship config the float32 m/v are ~7 GB resident.  Storing both
moments as int8 with per-block float32 scales cuts that to ~1.8 GB —
the headroom that decides whether the next model size fits on a chip.

Measured reality (v5e, flagship config, BASELINE.md round 3): step TIME
is at parity with f32 adamw (357 vs 351 ms) — the quantize/requantize
arithmetic costs what the state bandwidth saves on this part, so for
pure speed prefer ``adamw(mu_dtype=bfloat16)`` (326 ms).  Choose
adamw8bit for its MEMORY footprint.

Quantization scheme (chosen for XLA friendliness — everything is a
reshape + absmax + multiply, no tables):

- **m (first moment):** symmetric linear int8 per block of
  ``block_size`` values: ``q = round(m / s * 127)``, ``s = absmax``.
  Momentum is noise-tolerant; linear absmax is plenty (the same
  argument as optax's mu_dtype=bfloat16, just 2x smaller).
- **v (second moment):** nonnegative with a huge dynamic range, and the
  update consumes ``1/(sqrt(v)+eps)`` — linear quantization of v would
  crush small values.  Stored instead as ``sqrt(v)`` quantized with the
  UNSIGNED mapping (``signed=False``: the full int8 range covers
  [0, max], twice the resolution of the symmetric scheme on a
  nonnegative tensor); uniform error in the sqrt domain ≈ uniform error
  in the denominator, which keeps relative update error at the percent
  level (see tests/test_optim8bit.py for the convergence check vs f32
  adam).

The transform is a drop-in `optax.GradientTransformation`; compose decay
/ clipping around it exactly like `optax.scale_by_adam`:

    opt = optim8bit.adamw8bit(3e-4, weight_decay=0.1)
    # or via the factory: optim.make_optimizer("adamw8bit", ...)

Sharding note: quantized payloads are flat [n_blocks, block] views.  For
a param sharded on dim 0 only (fsdp-style), each shard owns a contiguous
flat range, so passing ``example_params`` to
``parallel.train.make_train_step`` shards q/scale along their block axis
with the same mesh axis — the int8 state then scales down per chip
exactly like f32 moments would.  Without shapes (or for non-dim-0
layouts) the train-step helpers REPLICATE this state with a loud warning
(parallel/train._map_state).
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 256


class Quantized(NamedTuple):
    """Blockwise-quantized tensor: int8 payload + per-block f32 scales.
    The original shape is NOT stored — `dequantize` takes it from the
    gradient it is paired with."""
    q: jnp.ndarray       # int8 [n_blocks, block]
    scale: jnp.ndarray   # f32  [n_blocks, 1]


def _pad_len(n, block):
    return (-n) % block


def quantize(x, block=DEFAULT_BLOCK, signed=True):
    """f32/bf16 array -> Quantized, linear absmax per block.

    ``signed=True``: symmetric int8 in [-127, 127] (first moment).
    ``signed=False``: for NONNEGATIVE tensors — the full int8 range maps
    [0, max] via ``q = round(x/s*254) - 127``, halving the step size the
    symmetric scheme would waste on the never-used negative half (matters
    for nu_sqrt, which the update consumes as 1/(sqrt(v)+eps)).
    """
    flat = x.reshape(-1).astype(jnp.float32)
    pad = _pad_len(flat.size, block)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, block)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    safe = jnp.where(scale > 0, scale, 1.0)
    if signed:
        q = jnp.clip(jnp.round(blocks / safe * 127.0), -127, 127)
    else:
        q = jnp.clip(jnp.round(blocks / safe * 254.0) - 127.0, -127, 127)
    return Quantized(q.astype(jnp.int8), scale)


def dequantize(qt, shape, dtype=jnp.float32, signed=True):
    if signed:
        flat = (qt.q.astype(jnp.float32) * (qt.scale / 127.0)).reshape(-1)
    else:
        flat = ((qt.q.astype(jnp.float32) + 127.0)
                * (qt.scale / 254.0)).reshape(-1)
    n = 1
    for d in shape:
        n *= d
    return flat[:n].reshape(shape).astype(dtype)


class Adam8bitState(NamedTuple):
    count: jnp.ndarray
    mu: object        # pytree of Quantized
    nu_sqrt: object   # pytree of Quantized (stores sqrt(v))


class _UpdOut(NamedTuple):
    """Per-leaf result triple of the update fn (a dedicated type so
    is_leaf can target it without colliding with tuple containers that
    may appear inside the user's parameter pytree)."""
    out: jnp.ndarray
    mu: Quantized
    nu_sqrt: Quantized


def scale_by_adam_8bit(b1=0.9, b2=0.999, eps=1e-8, block_size=DEFAULT_BLOCK):
    """`optax.scale_by_adam` with int8 blockwise state (see module doc)."""
    import optax

    def init_fn(params):
        # mu and nu_sqrt must be INDEPENDENT buffers: sharing one zero
        # tree would donate the same buffer twice under donated train
        # steps (XLA rejects `f(donate(a), donate(a))`)
        def zeros_q(signed):
            return lambda p: quantize(jnp.zeros(p.shape, jnp.float32),
                                      block_size, signed=signed)

        return Adam8bitState(
            jnp.zeros((), jnp.int32),
            jax.tree_util.tree_map(zeros_q(True), params),
            jax.tree_util.tree_map(zeros_q(False), params))

    def update_fn(updates, state, params=None):
        count = state.count + 1

        def upd(g, mu_q, nusq_q):
            g = g.astype(jnp.float32)
            mu = dequantize(mu_q, g.shape)
            v = dequantize(nusq_q, g.shape, signed=False) ** 2
            mu = b1 * mu + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mu_hat = mu / (1 - b1 ** count.astype(jnp.float32))
            v_hat = v / (1 - b2 ** count.astype(jnp.float32))
            out = mu_hat / (jnp.sqrt(v_hat) + eps)
            return _UpdOut(out, quantize(mu, block_size),
                           quantize(jnp.sqrt(v), block_size, signed=False))

        # tree_map flattens the companion trees UP TO `updates`' leaf
        # positions, so each call sees the whole Quantized subtree for
        # its parameter; `flat` then holds one _UpdOut per leaf position
        # (a dedicated type: keying is_leaf on bare tuples would misfire
        # on tuple CONTAINERS inside the parameter pytree)
        flat = jax.tree_util.tree_map(
            upd, updates, state.mu, state.nu_sqrt)
        is_out = lambda x: isinstance(x, _UpdOut)  # noqa: E731
        out = jax.tree_util.tree_map(lambda t: t.out, flat, is_leaf=is_out)
        mu = jax.tree_util.tree_map(lambda t: t.mu, flat, is_leaf=is_out)
        nusq = jax.tree_util.tree_map(lambda t: t.nu_sqrt, flat,
                                      is_leaf=is_out)
        return out, Adam8bitState(count, mu, nusq)

    return optax.GradientTransformation(init_fn, update_fn)


def adamw8bit(learning_rate, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
              mask=None, block_size=DEFAULT_BLOCK):
    """AdamW with 8-bit state: scale_by_adam_8bit -> weight decay -> lr."""
    import optax

    chain = [scale_by_adam_8bit(b1, b2, eps, block_size)]
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay, mask))
    chain.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)
