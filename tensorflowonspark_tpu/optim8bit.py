"""8-bit blockwise-quantized Adam state (bitsandbytes-style, TPU-native).

At LM scale the Adam states dominate training memory: for the 0.87B
flagship config the float32 m/v are ~7 GB resident.  Storing both
moments as int8 with per-block float32 scales cuts that to ~1.8 GB —
the headroom that decides whether the next model size fits on a chip.

Measured reality (v5e, flagship config, BASELINE.md round 3): step TIME
is at parity with f32 adamw (357 vs 351 ms) — the quantize/requantize
arithmetic costs what the state bandwidth saves on this part, so for
pure speed prefer ``adamw(mu_dtype=bfloat16)`` (326 ms).  Choose
adamw8bit for its MEMORY footprint.

Quantization scheme (chosen for XLA friendliness — everything is a
reshape + absmax + multiply, no tables):

- **m (first moment):** symmetric linear int8 per block of
  ``block_size`` values: ``q = round(m / s * 127)``, ``s = absmax``.
  Momentum is noise-tolerant; linear absmax is plenty (the same
  argument as optax's mu_dtype=bfloat16, just 2x smaller).
- **v (second moment):** nonnegative with a huge dynamic range, and the
  update consumes ``1/(sqrt(v)+eps)`` — linear quantization of v would
  crush small values.  Stored instead as ``sqrt(v)`` quantized with the
  UNSIGNED mapping (``signed=False``: the full int8 range covers
  [0, max], twice the resolution of the symmetric scheme on a
  nonnegative tensor); uniform error in the sqrt domain ≈ uniform error
  in the denominator, which keeps relative update error at the percent
  level (see tests/test_optim8bit.py for the convergence check vs f32
  adam).

The transform is a drop-in `optax.GradientTransformation`; compose decay
/ clipping around it exactly like `optax.scale_by_adam`:

    opt = optim8bit.adamw8bit(3e-4, weight_decay=0.1)
    # or via the factory: optim.make_optimizer("adamw8bit", ...)

Sharding note: quantized payloads are flat [n_blocks, block] views.  By
default the flatten is plain row-major, which only lines up with a param
sharded on dim 0 (fsdp-style row sharding).  For the general fsdp x tp
case — a matrix sharded on BOTH dims — build the optimizer with
``layouts=optim8bit.layouts_for_shardings(params, shardings)``:
quantization blocks are then computed over each logical shard's OWN
elements (shard-major flatten, per-shard padding), so q/scale shard
along their block axis by the param's full spec with zero extra
communication, and the int8 state scales down per chip exactly like f32
moments would.  Pass the SAME layouts tree to
``parallel.train.make_train_step(..., example_params=..., layouts=...)``
so it emits the matching state shardings (explicit, never guessed: an
aligned payload's shape coincides with the row-major one whenever each
shard's elements are a block multiple — the common production case).  A
layout-less 8-bit state under a TP-sharded param REPLICATES with a loud
warning (parallel/train._map_state).
"""
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 256


class Quantized(NamedTuple):
    """Blockwise-quantized tensor: int8 payload + per-block f32 scales.
    The original shape is NOT stored — `dequantize` takes it from the
    gradient it is paired with."""
    q: jnp.ndarray       # int8 [n_blocks, block]
    scale: jnp.ndarray   # f32  [n_blocks, 1]


def _pad_len(n, block):
    return (-n) % block


def _shard_major(x, layout):
    """Reshape `x` to [n_shards, elems_per_shard], shard-major.

    `layout` gives per-dim shard counts (n_0, ..., n_{r-1}); every dim
    must divide.  Row k of the result is exactly the elements device k
    owns under a PartitionSpec whose dim-i axes have total size n_i —
    shard order matches GSPMD's (dim-major, then major-to-minor within a
    tuple spec entry), so sharding the result's dim 0 by the concatenated
    spec axes keeps every block device-local.
    """
    r = len(x.shape)
    split = []
    for d, n in zip(x.shape, layout):
        split.extend((n, d // n))
    perm = ([2 * i for i in range(r)] + [2 * i + 1 for i in range(r)])
    return x.reshape(split).transpose(perm).reshape(math.prod(layout), -1)


def _shard_major_inverse(flat, shape, layout):
    """Invert `_shard_major`: [n_shards, elems_per_shard] -> `shape`."""
    r = len(shape)
    sub = tuple(d // n for d, n in zip(shape, layout))
    perm = []
    for i in range(r):
        perm.extend((i, r + i))
    return flat.reshape(tuple(layout) + sub).transpose(perm).reshape(shape)


def quantize(x, block=DEFAULT_BLOCK, signed=True, layout=None):
    """f32/bf16 array -> Quantized, linear absmax per block.

    ``signed=True``: symmetric int8 in [-127, 127] (first moment).
    ``signed=False``: for NONNEGATIVE tensors — the full int8 range maps
    [0, max] via ``q = round(x/s*254) - 127``, halving the step size the
    symmetric scheme would waste on the never-used negative half (matters
    for nu_sqrt, which the update consumes as 1/(sqrt(v)+eps)).

    ``layout`` (per-dim shard counts, from `shard_layout`): blocks are
    computed over each logical shard's own elements — shard-major
    flatten with per-shard padding — so the payload's dim 0 shards by
    the param's full PartitionSpec with no cross-shard blocks.  The
    same `layout` must be passed to `dequantize`.
    """
    layout = _check_layout(layout, x.shape)
    if layout is None:
        flat = x.reshape(1, -1).astype(jnp.float32)
    else:
        flat = _shard_major(x.astype(jnp.float32), layout)
    pad = _pad_len(flat.shape[1], block)
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    blocks = flat.reshape(-1, block)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    safe = jnp.where(scale > 0, scale, 1.0)
    if signed:
        q = jnp.clip(jnp.round(blocks / safe * 127.0), -127, 127)
    else:
        q = jnp.clip(jnp.round(blocks / safe * 254.0) - 127.0, -127, 127)
    return Quantized(q.astype(jnp.int8), scale)


def dequantize(qt, shape, dtype=jnp.float32, signed=True, layout=None):
    if signed:
        flat = (qt.q.astype(jnp.float32) * (qt.scale / 127.0)).reshape(-1)
    else:
        flat = ((qt.q.astype(jnp.float32) + 127.0)
                * (qt.scale / 254.0)).reshape(-1)
    layout = _check_layout(layout, shape)
    if layout is None:
        return flat[:math.prod(shape)].reshape(shape).astype(dtype)
    n_shards = math.prod(layout)
    block = qt.q.shape[-1]
    if qt.q.shape[0] != expected_blocks(shape, layout, block):
        raise ValueError(
            f"payload {tuple(qt.q.shape)} was not quantized with layout "
            f"{layout} for shape {shape} (expected "
            f"{expected_blocks(shape, layout, block)} blocks)")
    flat = flat.reshape(n_shards, -1)[:, :math.prod(shape) // n_shards]
    return _shard_major_inverse(flat, shape, layout).astype(dtype)


def _check_layout(layout, shape):
    """Validate `layout` against `shape`; normalize all-ones to None."""
    if layout is None:
        return None
    if len(layout) != len(shape) or any(
            d % n for d, n in zip(shape, layout)):
        raise ValueError(f"layout {layout} does not tile shape "
                         f"{tuple(shape)}")
    return None if all(n == 1 for n in layout) else tuple(layout)


def expected_blocks(shape, layout, block):
    """Block-row count of a payload quantized with `layout` (per-shard
    padding: each shard's elements round up to whole blocks)."""
    n_shards = math.prod(layout)
    per_shard = math.prod(shape) // n_shards
    return n_shards * (-(-per_shard // block))


def shard_layout(shape, sharding):
    """Per-dim shard counts for a param under `sharding`, or None.

    Returns a tuple (n_0, ..., n_{r-1}) — the number of shards along
    each dim implied by the sharding's PartitionSpec over its mesh —
    when at least one dim is sharded and every sharded dim divides.
    None means "no aligned layout": unsharded, scalar, indivisible, or
    a plain positional sharding we cannot read a spec from.
    """
    spec = tuple(getattr(sharding, "spec", ()) or ())
    mesh = getattr(sharding, "mesh", None)
    if mesh is None or not shape:
        return None
    counts = []
    for i, d in enumerate(shape):
        entry = spec[i] if i < len(spec) else None
        names = (() if entry is None
                 else entry if isinstance(entry, tuple) else (entry,))
        n = math.prod(mesh.shape.get(a, 1) for a in names)
        if n > 1 and d % n:
            return None
        counts.append(n)
    if all(n == 1 for n in counts):
        return None
    return tuple(counts)


def layouts_for_shardings(params, shardings):
    """Pytree of `shard_layout` results matching `params`, for the
    ``layouts=`` argument of `adamw8bit` / `scale_by_adam_8bit`.

    Build the optimizer with this whenever params are sharded (fsdp
    and/or tp) so the int8 state shards with them; pass the same
    `shardings` (and `example_params`) to
    `parallel.train.make_train_step`, which recognizes the layout and
    emits matching state shardings.
    """
    return jax.tree_util.tree_map(
        lambda p, s: shard_layout(tuple(getattr(p, "shape", ())), s),
        params, shardings)


class Adam8bitState(NamedTuple):
    count: jnp.ndarray
    mu: object        # pytree of Quantized
    nu_sqrt: object   # pytree of Quantized (stores sqrt(v))


class _UpdOut(NamedTuple):
    """Per-leaf result triple of the update fn (a dedicated type so
    is_leaf can target it without colliding with tuple containers that
    may appear inside the user's parameter pytree)."""
    out: jnp.ndarray
    mu: Quantized
    nu_sqrt: Quantized


def scale_by_adam_8bit(b1=0.9, b2=0.999, eps=1e-8, block_size=DEFAULT_BLOCK,
                       layouts=None):
    """`optax.scale_by_adam` with int8 blockwise state (see module doc).

    ``layouts`` (pytree matching params; leaves are per-dim shard-count
    tuples or None — from `layouts_for_shardings`) aligns each param's
    quantization blocks to its logical shards so the state can shard by
    the param's full PartitionSpec.  Pure layout: the update math is
    identical, only block boundaries move.
    """
    import optax

    def _layout_tree(params):
        if layouts is None:
            return jax.tree_util.tree_map(lambda _: None, params)
        return layouts

    def init_fn(params):
        # mu and nu_sqrt must be INDEPENDENT buffers: sharing one zero
        # tree would donate the same buffer twice under donated train
        # steps (XLA rejects `f(donate(a), donate(a))`)
        def zeros_q(signed):
            return lambda p, lo: quantize(jnp.zeros(p.shape, jnp.float32),
                                          block_size, signed=signed,
                                          layout=lo)

        lts = _layout_tree(params)
        return Adam8bitState(
            jnp.zeros((), jnp.int32),
            jax.tree_util.tree_map(zeros_q(True), params, lts),
            jax.tree_util.tree_map(zeros_q(False), params, lts))

    def update_fn(updates, state, params=None):
        count = state.count + 1

        def upd(g, mu_q, nusq_q, lo):
            g = g.astype(jnp.float32)
            mu = dequantize(mu_q, g.shape, layout=lo)
            v = dequantize(nusq_q, g.shape, signed=False, layout=lo) ** 2
            mu = b1 * mu + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mu_hat = mu / (1 - b1 ** count.astype(jnp.float32))
            v_hat = v / (1 - b2 ** count.astype(jnp.float32))
            out = mu_hat / (jnp.sqrt(v_hat) + eps)
            return _UpdOut(out, quantize(mu, block_size, layout=lo),
                           quantize(jnp.sqrt(v), block_size, signed=False,
                                    layout=lo))

        # tree_map flattens the companion trees UP TO `updates`' leaf
        # positions, so each call sees the whole Quantized subtree for
        # its parameter; `flat` then holds one _UpdOut per leaf position
        # (a dedicated type: keying is_leaf on bare tuples would misfire
        # on tuple CONTAINERS inside the parameter pytree)
        flat = jax.tree_util.tree_map(
            upd, updates, state.mu, state.nu_sqrt, _layout_tree(updates))
        is_out = lambda x: isinstance(x, _UpdOut)  # noqa: E731
        out = jax.tree_util.tree_map(lambda t: t.out, flat, is_leaf=is_out)
        mu = jax.tree_util.tree_map(lambda t: t.mu, flat, is_leaf=is_out)
        nusq = jax.tree_util.tree_map(lambda t: t.nu_sqrt, flat,
                                      is_leaf=is_out)
        return out, Adam8bitState(count, mu, nusq)

    return optax.GradientTransformation(init_fn, update_fn)


def adamw8bit(learning_rate, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
              mask=None, block_size=DEFAULT_BLOCK, layouts=None):
    """AdamW with 8-bit state: scale_by_adam_8bit -> weight decay -> lr."""
    import optax

    chain = [scale_by_adam_8bit(b1, b2, eps, block_size, layouts=layouts)]
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay, mask))
    chain.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*chain)
