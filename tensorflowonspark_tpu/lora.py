"""LoRA — low-rank adaptation for parameter-efficient fine-tuning.

Pairs with `convert` (import a GPT-2/BERT checkpoint, then fine-tune
adapters only): instead of touching the model definition, LoRA here is a
functional transform over the param tree —

    adapters = lora.init(rng, params, rank=8)          # A/B per target kernel
    tuned = lora.merge(params, adapters, scale=1.0)    # W + scale·A@B
    logits = model.apply({"params": tuned}, tokens)

Training freezes the base params by construction — they are a captured
constant, not an argument, so differentiating the wrapped loss w.r.t. the
adapter tree is all it takes (no stop_gradient bookkeeping).  Adapters
are `rank*(d_in+d_out)` per `d_in*d_out` kernel.  This composes with
every framework feature unchanged:
the merged tree has the SAME structure as `params`, so sharding rules,
checkpointing, export, and `models.decode.generate` all apply.

TPU notes: `merge` is two skinny matmuls + an add per target — negligible
next to a forward pass and fully fusable by XLA; merged once per step
under jit, not per layer-call.
"""
import logging
import re

logger = logging.getLogger(__name__)

# kernels adapted by default: attention projections (the standard LoRA
# placement) — match path segments like "attn/query/kernel"
DEFAULT_TARGETS = r"(query|key|value|out)/kernel$"


from .treeutil import flatten_with_paths as _flatten  # shared path scheme


def target_paths(params, targets=DEFAULT_TARGETS):
    """Paths (slash-joined) of the kernels a pattern selects."""
    flat, _ = _flatten(params)
    pat = re.compile(targets)
    return [k for k, v in flat.items()
            if pat.search(k) and getattr(v, "ndim", 0) == 2]


def init(rng, params, rank=8, targets=DEFAULT_TARGETS):
    """Build the adapter tree: {path: {"a": [in, r], "b": [r, out]}}.

    `a` is gaussian-initialized, `b` zeros — so the merged model starts
    EXACTLY at the base model (standard LoRA init).  The tree contains
    only float arrays, so it IS the trainable pytree (differentiate and
    optimize it directly); the usual alpha/rank factor is the `scale`
    argument of `merge`.
    """
    import jax
    import jax.numpy as jnp

    flat, _ = _flatten(params)
    pat = re.compile(targets)
    paths = [k for k, v in flat.items()
             if pat.search(k) and getattr(v, "ndim", 0) == 2]
    if not paths:
        raise ValueError(f"no 2-D kernels match targets={targets!r}")
    adapters = {}
    keys = jax.random.split(rng, len(paths))
    for key, path in zip(keys, paths):
        w = flat[path]
        d_in, d_out = w.shape
        adapters[path] = {
            "a": (jax.random.normal(key, (d_in, rank), jnp.float32)
                  * (1.0 / rank)),
            "b": jnp.zeros((rank, d_out), jnp.float32),
        }
    logger.info("LoRA: rank=%d adapters on %d kernels (%.2fM trainable)",
                rank, len(paths),
                sum(a["a"].size + a["b"].size
                    for a in adapters.values()) / 1e6)
    return adapters


def merge(params, adapters, scale=1.0):
    """Return params with `W + scale * A @ B` on every adapted kernel —
    same tree structure as `params` (jit/vjp-friendly).  `scale` is the
    usual LoRA alpha/rank factor."""
    import jax
    import jax.numpy as jnp

    flat, treedef = _flatten(params)
    unused = set(adapters) - set(flat)
    if unused:
        raise ValueError(
            "adapter paths not found in params (trained on a different "
            f"tree/scope?): {sorted(unused)[:4]}...")
    leaves = []
    for key in flat:
        w = flat[key]
        ad = adapters.get(key)
        if ad is None:
            leaves.append(w)
        else:
            delta = (ad["a"] @ ad["b"]) * scale
            leaves.append((w.astype(jnp.float32) + delta).astype(w.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def make_lora_loss(loss_fn, base_params, scale=1.0):
    """Wrap `loss_fn(params, batch, rng)` into
    `lora_loss(adapters, batch, rng)` that differentiates only the
    adapters (base params are captured, not arguments — so
    `parallel.train.make_train_step(lora_loss, opt)` trains adapters
    only, with optimizer state sized to the adapters)."""
    def lora_loss(adapters, batch, rng):
        return loss_fn(merge(base_params, adapters, scale), batch, rng)
    return lora_loss


def num_trainable(adapters):
    return sum(a["a"].size + a["b"].size for a in adapters.values())


def save_adapters(path, adapters, scale=1.0):
    """Persist an adapter tree (+ its merge scale) as one msgpack file —
    the artifact `serve`'s multi-adapter bank loads per tenant
    (``--generate_lora name=path``).  fs-agnostic via fsio (local/HDFS
    paths like every other artifact)."""
    import flax.serialization

    from . import fsio

    if not adapters:
        raise ValueError("adapters tree is empty — nothing to save")
    rank = next(iter(adapters.values()))["a"].shape[-1]
    blob = flax.serialization.msgpack_serialize(
        {"adapters": {k: {"a": v["a"], "b": v["b"]}
                      for k, v in adapters.items()},
         "meta": {"scale": float(scale), "rank": rank}})
    with fsio.fopen(path, "wb") as f:
        f.write(blob)


def load_adapters(path):
    """Restore ``(adapters, scale)`` written by `save_adapters`."""
    import flax.serialization

    from . import fsio

    with fsio.fopen(path, "rb") as f:
        obj = flax.serialization.msgpack_restore(f.read())
    if not isinstance(obj, dict) or "adapters" not in obj:
        raise ValueError(f"{path!r} is not a saved LoRA adapter file")
    return obj["adapters"], float(obj.get("meta", {}).get("scale", 1.0))
