"""Auxiliary subsystems: checkpointing, profiling (SURVEY.md §5)."""
