"""Profiling/observability: the TPU-native replacement for the reference's
TensorBoard subprocess (SURVEY.md §5 "Tracing/profiling"; reference:
TFSparkNode.py:282-319 launched `tensorboard` on chief and surfaced the URL).

Here the chief starts the JAX profiler server (connectable from TensorBoard's
profile plugin or `jax.profiler.trace`) and, when the tensorboard binary is
on PATH, optionally a TensorBoard subprocess over the log dir.
"""
import contextlib
import logging
import os
import shutil
import subprocess

logger = logging.getLogger(__name__)

_profiler_started = False


def start_profiler_server(port=9012):
    """Start the JAX profiler gRPC server (idempotent)."""
    global _profiler_started
    if _profiler_started:
        return port
    import jax
    jax.profiler.start_server(port)
    _profiler_started = True
    logger.info("jax profiler server listening on %d", port)
    return port


@contextlib.contextmanager
def trace(log_dir):
    """Capture a profiler trace viewable in TensorBoard/Perfetto."""
    import jax
    with jax.profiler.trace(log_dir):
        yield
    logger.info("profiler trace written to %s", log_dir)


def start_tensorboard(log_dir, port=None):
    """Launch a TensorBoard subprocess if the binary is available.

    Returns (pid, port, url) or None.  Mirrors the reference's PATH search +
    TENSORBOARD_PORT/ephemeral port behavior (TFSparkNode.py:288-311).
    """
    binary = shutil.which("tensorboard")
    if binary is None:
        logger.warning("tensorboard not found on PATH; skipping")
        return None
    from .. import util
    port = port or int(os.environ.get("TENSORBOARD_PORT", 0)) or \
        util.get_free_port()
    proc = subprocess.Popen(
        [binary, "--logdir", log_dir, "--port", str(port), "--bind_all"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    url = f"http://{util.get_ip_address()}:{port}"
    logger.info("tensorboard pid=%d at %s", proc.pid, url)
    return proc.pid, port, url


def stop_tensorboard(pid):
    """Kill the TensorBoard subprocess (reference: TFSparkNode.py:599-605)."""
    import signal
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
