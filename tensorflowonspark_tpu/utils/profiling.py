"""Profiling/observability: the TPU-native replacement for the reference's
TensorBoard subprocess (SURVEY.md §5 "Tracing/profiling"; reference:
TFSparkNode.py:282-319 launched `tensorboard` on chief and surfaced the URL).

Here the chief starts the JAX profiler server (connectable from TensorBoard's
profile plugin or `jax.profiler.trace`) and, when the tensorboard binary is
on PATH, optionally a TensorBoard subprocess over the log dir.
"""
import contextlib
import logging
import os
import shutil
import subprocess

logger = logging.getLogger(__name__)

_profiler_started = False


def start_profiler_server(port=9012):
    """Start the JAX profiler gRPC server (idempotent)."""
    global _profiler_started
    if _profiler_started:
        return port
    import jax
    jax.profiler.start_server(port)
    _profiler_started = True
    logger.info("jax profiler server listening on %d", port)
    return port


@contextlib.contextmanager
def trace(log_dir):
    """Capture a profiler trace viewable in TensorBoard/Perfetto."""
    import jax
    with jax.profiler.trace(log_dir):
        yield
    logger.info("profiler trace written to %s", log_dir)


def parse_perfetto_trace(path_or_events, device_only=True, group=True):
    """Aggregate a perfetto trace (`jax.profiler` with
    ``create_perfetto_trace=True``) into per-op device time.

    Returns [(name, total_dur_us, count)] sorted by time desc.  `group`
    collapses versioned XLA op names ("fusion.123" -> "fusion"); set
    False for the per-instance view.  Accepts a path to
    ``perfetto_trace.json.gz``/.json, a trace dict, or an event list.
    """
    import collections
    import gzip
    import json

    if isinstance(path_or_events, str):
        opener = (gzip.open if path_or_events.endswith(".gz") else open)
        with opener(path_or_events, "rt") as f:
            trace = json.load(f)
        events = trace.get("traceEvents", [])
    elif isinstance(path_or_events, dict):
        events = path_or_events.get("traceEvents", [])
    else:
        events = path_or_events

    pids = {ev.get("pid"): ev.get("args", {}).get("name", "")
            for ev in events
            if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    dur = collections.Counter()
    cnt = collections.Counter()
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        track = pids.get(ev.get("pid"), "")
        if device_only and not ("TPU" in track or "GPU" in track
                                or "/device:" in track):
            continue
        name = ev.get("name", "?")
        if group:
            name = name.split(".")[0]
        dur[name] += ev["dur"]
        cnt[name] += 1
    return [(name, d, cnt[name]) for name, d in dur.most_common()]


def op_breakdown(fn, *args, steps=3, log_dir=None, top=20):
    """Run `fn(*args)` under the profiler and return the per-op device-time
    breakdown — the 'where does my step go' question in one call.

    `fn` should be the jitted step (warmed up by this helper); the
    result's scale is `steps` executions.  Returns
    [(op_name, total_us, count)]; also logs the top entries.
    """
    import glob
    import tempfile

    import jax
    import numpy as np

    def _sync(out):
        # host readback of every leaf: block_until_ready can return early
        # under tunneled device plugins (see BASELINE.md methodology note)
        for leaf in jax.tree_util.tree_leaves(out):
            np.asarray(leaf)

    _sync(fn(*args))                      # warmup/compile
    log_dir = log_dir or tempfile.mkdtemp(prefix="tfos_profile_")
    jax.profiler.start_trace(log_dir, create_perfetto_trace=True)
    out = None
    for _ in range(steps):
        out = fn(*args)
    _sync(out)
    jax.profiler.stop_trace()
    traces = glob.glob(os.path.join(log_dir, "**", "perfetto_trace.json.gz"),
                       recursive=True)
    if not traces:
        raise RuntimeError(f"no perfetto trace produced under {log_dir}")
    rows = parse_perfetto_trace(sorted(traces)[-1])
    for name, us, n in rows[:top]:
        logger.info("%10.3f ms/step x%-5d %s", us / 1e3 / steps, n // steps,
                    name)
    return rows


def start_tensorboard(log_dir, port=None):
    """Launch a TensorBoard subprocess if the binary is available.

    Returns (pid, port, url) or None.  Mirrors the reference's PATH search +
    TENSORBOARD_PORT/ephemeral port behavior (TFSparkNode.py:288-311).
    """
    binary = shutil.which("tensorboard")
    if binary is None:
        logger.warning("tensorboard not found on PATH; skipping")
        return None
    from .. import util
    port = port or int(os.environ.get("TENSORBOARD_PORT", 0)) or \
        util.get_free_port()
    proc = subprocess.Popen(
        [binary, "--logdir", log_dir, "--port", str(port), "--bind_all"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    url = f"http://{util.get_ip_address()}:{port}"
    logger.info("tensorboard pid=%d at %s", proc.pid, url)
    return proc.pid, port, url


def stop_tensorboard(pid):
    """Kill the TensorBoard subprocess (reference: TFSparkNode.py:599-605)."""
    import signal
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        pass
