"""Dependency-free TensorBoard scalar logging (tfevents format).

The reference's only training observability is the TensorBoard subprocess it
launches next to the chief (reference: TFSparkNode.py:282-319) — the actual
summaries come from TF inside user code.  Here the framework owns the metric
stream: `SummaryWriter` emits TensorBoard-readable event files with no
TensorFlow dependency, by hand-encoding the two tiny protos involved
(`Event`, `Summary`) and framing them with the same masked-CRC32C record
format as the TFRecord layer (tfrecord.py, which also provides the
C-accelerated CRC when the native lib is built).

Wire format refresher (proto3): each field is a key varint
``(field_number << 3) | wire_type`` followed by the payload; wire types used
here are 0 (varint), 1 (fixed64), 2 (length-delimited), 5 (fixed32).
"""
import os
import socket
import struct
import time

from tensorflowonspark_tpu import tfrecord


def _varint(n):
    out = bytearray()
    n &= (1 << 64) - 1  # proto int64: negatives encode as 10-byte two's complement
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field, wire):
    return _varint((field << 3) | wire)


def _len_delimited(field, payload):
    return _key(field, 2) + _varint(len(payload)) + payload


def _encode_scalar_event(tag, value, step, wall_time):
    # Summary.Value: tag = field 1 (bytes), simple_value = field 2 (float)
    val = (_len_delimited(1, tag.encode("utf-8"))
           + _key(2, 5) + struct.pack("<f", float(value)))
    summary = _len_delimited(1, val)        # Summary.value = repeated field 1
    return (_key(1, 1) + struct.pack("<d", wall_time)   # Event.wall_time
            + _key(2, 0) + _varint(int(step))           # Event.step
            + _len_delimited(5, summary))               # Event.summary


def _encode_file_version(wall_time):
    return (_key(1, 1) + struct.pack("<d", wall_time)
            + _len_delimited(3, b"brain.Event:2"))      # Event.file_version


class SummaryWriter:
    """Writes TensorBoard scalar events under `log_dir`.

    Usage (typically chief-only, next to utils.profiling's TensorBoard
    launch):

        sw = SummaryWriter(log_dir)
        sw.scalar("train/loss", loss, step)
        sw.close()
    """

    # flush after this many buffered events or this many seconds, whichever
    # first — a live TensorBoard next to the chief sees fresh curves, and an
    # ungracefully-killed worker loses at most one small tail
    FLUSH_EVERY = 16
    FLUSH_SECS = 2.0

    def __init__(self, log_dir, filename_suffix=""):
        os.makedirs(log_dir, exist_ok=True)
        name = (f"events.out.tfevents.{int(time.time())}."
                f"{socket.gethostname()}.{os.getpid()}{filename_suffix}")
        self.path = os.path.join(log_dir, name)
        self._writer = tfrecord.TFRecordWriter(self.path)
        self._writer.write(_encode_file_version(time.time()))
        self._pending = 0
        self._last_flush = time.monotonic()
        self.flush()

    def scalar(self, tag, value, step, wall_time=None):
        """Log one scalar point; shows up as a TensorBoard curve per tag."""
        self._writer.write(_encode_scalar_event(
            tag, value, step, time.time() if wall_time is None else wall_time))
        self._pending += 1
        if (self._pending >= self.FLUSH_EVERY
                or time.monotonic() - self._last_flush >= self.FLUSH_SECS):
            self.flush()

    def scalars(self, metrics, step, prefix=""):
        """Log a dict of name -> value at one step (e.g. a train_step's
        metrics pytree of scalars).  Flushing rides `scalar`'s
        event-count/age policy so batched callers (DeferredScalars) don't
        pay one file flush per step."""
        for name, value in metrics.items():
            self.scalar(prefix + name, value, step)

    def flush(self):
        self._writer.flush()
        self._pending = 0
        self._last_flush = time.monotonic()

    def close(self):
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DeferredScalars:
    """Buffers per-step metric pytrees as *device* scalars and reads them
    back in batches.

    ``float(metrics["loss"])`` every step forces a host<->device round
    trip per step, serializing dispatch with execution (a pipeline bubble
    that can dwarf the step itself on high-latency links).  Appending the
    raw device scalars instead lets the device run ahead; `flush()`
    stacks each tag's buffered scalars into one array and performs ONE
    readback per tag, forwarding the floats to an optional sink
    (`SummaryWriter.scalars`-compatible) and accumulating running means.
    """

    def __init__(self, sink=None, every=64, prefix=""):
        self._sink = sink
        self._every = max(1, int(every))
        self._prefix = prefix
        self._buf = []                      # [(step, {tag: device scalar})]
        self._totals = {}                   # tag -> (sum, count)
        self._last = {}                     # tag -> most recent flushed value

    def append(self, metrics, step):
        """Record one step's metrics dict WITHOUT reading back; flushes
        automatically every `every` appends."""
        self._buf.append((int(step), dict(metrics)))
        if len(self._buf) >= self._every:
            self.flush()

    def flush(self):
        """Read back all buffered scalars (one transfer per tag) and
        forward them to the sink.  Returns [(step, {tag: float})]."""
        if not self._buf:
            return []
        import numpy as np

        # union of tags across entries: tags may appear late or
        # intermittently (e.g. eval metrics every k steps)
        tags = []
        for _, m in self._buf:
            for tag in m:
                if tag not in tags:
                    tags.append(tag)
        cols = {}                           # tag -> iterator of floats
        for tag in tags:
            vals = [m[tag] for _, m in self._buf if tag in m]
            try:
                import jax.numpy as jnp
                col = np.asarray(jnp.stack(vals))
            except Exception:   # non-array values (plain floats/ints)
                col = np.asarray(vals)
            cols[tag] = iter([float(v) for v in col])
        out = [(step, {tag: next(cols[tag]) for tag in tags if tag in m})
               for step, m in self._buf]
        # commit before side effects: a sink failure must not leave the
        # buffer re-flushable (double-counting totals, duplicate events)
        self._buf.clear()
        for _, fm in out:
            for tag, v in fm.items():
                s, c = self._totals.get(tag, (0.0, 0))
                self._totals[tag] = (s + v, c + 1)
                self._last[tag] = v
        if self._sink is not None:
            for step, fm in out:
                self._sink.scalars(fm, step, prefix=self._prefix)
            if hasattr(self._sink, "flush"):
                self._sink.flush()  # one file flush per batch, not per step
        return out

    def mean(self, tag):
        """Running mean of a tag over everything flushed so far."""
        s, c = self._totals.get(tag, (0.0, 0))
        return s / c if c else float("nan")

    def count(self, tag):
        s, c = self._totals.get(tag, (0.0, 0))
        return c

    def last(self, tag):
        """Most recently flushed value of a tag (nan before any flush)."""
        return self._last.get(tag, float("nan"))


def read_scalars(path):
    """Parse a tfevents file back into [(step, tag, value)] — the symmetric
    reader (used by tests; also handy for headless metric scraping)."""
    out = []
    for record in tfrecord.read_records(path):
        step, summary = 0, None
        for field, wire, payload in _walk(record):
            if field == 2 and wire == 0:
                step = payload
            elif field == 5 and wire == 2:
                summary = payload
        if summary is None:
            continue
        for field, wire, payload in _walk(summary):
            if field == 1 and wire == 2:        # Summary.value entry
                tag, value = None, None
                for f2, w2, p2 in _walk(payload):
                    if f2 == 1 and w2 == 2:
                        tag = p2.decode("utf-8")
                    elif f2 == 2 and w2 == 5:
                        value = struct.unpack("<f", p2)[0]
                if tag is not None and value is not None:
                    out.append((step, tag, value))
    return out


def _walk(buf):
    """Yield (field, wire_type, payload) over one proto message's fields."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            payload, i = _read_varint(buf, i)
        elif wire == 1:
            payload, i = buf[i:i + 8], i + 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            payload, i = buf[i:i + ln], i + ln
        elif wire == 5:
            payload, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, payload


def _read_varint(buf, i):
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7
