"""Checkpoint/resume via Orbax, with the reference's chief-export semantics.

The reference delegated checkpointing to TF callbacks inside user code and
contributed path normalization + chief-only export + a grace period so the
chief can finish writing after feeding stops (SURVEY.md §5 "Checkpoint /
resume"; compat.py:10-17, TFCluster.py:125).  Here the framework provides
the equivalents natively: multi-host-safe Orbax saves, chief-only gating,
and step-numbered checkpoint directories with latest-step discovery.
"""
import logging
import os
import re

logger = logging.getLogger(__name__)

_STEP_DIR = re.compile(r"^step_(\d+)$")


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


_async_ckptr = None


def _async_checkpointer():
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp
        _async_ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _async_ckptr


def save_checkpoint(ckpt_dir, state, step, is_chief=True, keep=None,
                    asynchronous=False):
    """Save `state` (a pytree) under ckpt_dir/step_N.

    Non-chief processes no-op (single-controller semantics; under real
    multi-host jax.distributed, orbax coordinates internally and every
    process must call — pass is_chief=True on all hosts in that case).

    `asynchronous=True` returns as soon as the device->host copy is done
    and the write continues on a background thread — training resumes
    while bytes land on disk (the multi-host async checkpointing SURVEY.md
    §5 calls for).  Call `wait_for_saves()` before reading the checkpoint
    back or exiting the process.
    """
    if not is_chief:
        return None
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{int(step)}")
    if asynchronous:
        import orbax.checkpoint as ocp
        if keep:
            # prune completed steps down to keep-1 BEFORE enqueueing: once
            # this save commits, exactly `keep` checkpoints remain — the
            # same steady state as the sync path
            _prune(ckpt_dir, keep - 1)
        ckptr = _async_checkpointer()
        ckptr.save(path, args=ocp.args.StandardSave(state), force=True)
        logger.info("async checkpoint save started: %s", path)
        return path
    ckptr = _checkpointer()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()
    logger.info("saved checkpoint %s", path)
    if keep:
        _prune(ckpt_dir, keep)
    return path


def wait_for_saves():
    """Block until every in-flight asynchronous save has committed."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


class PreemptionHandler:
    """Handle of an installed preemption hook: ``uninstall()`` restores
    the previous signal handlers; ``guard()`` is a context manager that
    BLOCKS the signals for its body — wrap any region where the state the
    save_fn reads is transiently invalid.  The canonical case is a
    donated train step: the input state's buffers are deleted at dispatch
    and the fresh state only becomes publishable after the call returns,
    so a signal landing inside that window would save garbage (or
    nothing).  A signal received while blocked is delivered on unblock.
    """

    def __init__(self, previous):
        self._previous = previous

    def uninstall(self):
        import signal as signal_mod
        for sig, prev in self._previous.items():
            try:
                signal_mod.signal(sig, prev)
            except (ValueError, OSError):
                pass

    # kept callable for the uninstall-style usage
    __call__ = uninstall

    def guard(self):
        import contextlib
        import signal as signal_mod

        sigs = set(self._previous)

        @contextlib.contextmanager
        def _guard():
            # restore the PREVIOUS mask, not a blanket unblock: nested
            # guards (or a caller that blocked these signals itself) must
            # stay protected when an inner guard exits
            old = signal_mod.pthread_sigmask(signal_mod.SIG_BLOCK, sigs)
            try:
                yield
            finally:
                signal_mod.pthread_sigmask(signal_mod.SIG_SETMASK, old)
        return _guard()


def install_preemption_handler(save_fn, signals=None):
    """Save a final checkpoint when the process is told to die.

    TPU-VM preemptions and Spark executor decommissions deliver SIGTERM
    with a grace window before the hard kill; the reference had no
    equivalent (its checkpointing lived in TF callbacks that only fire on
    epoch boundaries).  ``save_fn()`` runs at most once, from the signal
    handler in the main thread — keep it to a synchronous
    ``save_checkpoint`` + ``wait_for_saves``.  After it returns, the
    process exits 128+signum (the conventional killed-by-signal code) so
    the scheduler still sees a signal death, not a success.

    Returns a `PreemptionHandler`; call its ``uninstall()`` after clean
    shutdown so a late SIGTERM in teardown does not re-save, and wrap
    donated train steps in ``handler.guard()`` so the signal cannot fire
    while the checkpointable state is mid-donation.  Must be called from
    the main thread (CPython restricts ``signal.signal`` to it).
    """
    import signal as signal_mod
    import sys

    signals = signals or (signal_mod.SIGTERM,)
    fired = []
    previous = {}

    def handler(signum, frame):
        if fired:
            # re-delivered signal while the first invocation is still
            # saving (schedulers commonly TERM the process group twice):
            # returning lets the in-progress save finish and exit —
            # sys.exit here would raise SystemExit INSIDE save_fn and
            # abort the very checkpoint this handler exists to write
            return
        fired.append(signum)
        try:
            logger.warning("signal %d: saving preemption checkpoint",
                           signum)
            save_fn()
            wait_for_saves()
            logger.warning("preemption checkpoint committed")
        except Exception:
            logger.exception("preemption save failed")
        sys.exit(128 + signum)

    for sig in signals:
        previous[sig] = signal_mod.signal(sig, handler)
    return PreemptionHandler(previous)


def restore_checkpoint(ckpt_dir, target, step=None):
    """Restore the pytree saved at `step` (default: latest).

    `target` is an example pytree (same structure/shapes) — with sharded
    arrays, pass abstract shapes carrying shardings for direct-to-device
    restore.
    """
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            return None, None
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{int(step)}")
    restored = _checkpointer().restore(path, target)
    logger.info("restored checkpoint %s", path)
    return restored, step


def latest_step(ckpt_dir):
    """Largest step number with a checkpoint under ckpt_dir, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [int(m.group(1)) for d in os.listdir(ckpt_dir)
             if (m := _STEP_DIR.match(d))]
    return max(steps) if steps else None


def _prune(ckpt_dir, keep):
    """Remove all but the newest `keep` completed checkpoints (0 = all)."""
    import shutil
    steps = sorted(int(m.group(1)) for d in os.listdir(ckpt_dir)
                   if (m := _STEP_DIR.match(d)))
    for s in steps[:-keep] if keep else steps:
        shutil.rmtree(os.path.join(ckpt_dir, f"step_{s}"), ignore_errors=True)
        logger.info("pruned checkpoint step_%d", s)
