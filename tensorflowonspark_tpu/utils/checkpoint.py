"""Checkpoint/resume via Orbax, with the reference's chief-export semantics.

The reference delegated checkpointing to TF callbacks inside user code and
contributed path normalization + chief-only export + a grace period so the
chief can finish writing after feeding stops (SURVEY.md §5 "Checkpoint /
resume"; compat.py:10-17, TFCluster.py:125).  Here the framework provides
the equivalents natively: multi-host-safe Orbax saves, chief-only gating,
and step-numbered checkpoint directories with latest-step discovery.
"""
import logging
import os
import re

logger = logging.getLogger(__name__)

_STEP_DIR = re.compile(r"^step_(\d+)$")


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_checkpoint(ckpt_dir, state, step, is_chief=True, keep=None):
    """Save `state` (a pytree) under ckpt_dir/step_N.

    Non-chief processes no-op (single-controller semantics; under real
    multi-host jax.distributed, orbax coordinates internally and every
    process must call — pass is_chief=True on all hosts in that case).
    """
    if not is_chief:
        return None
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{int(step)}")
    ckptr = _checkpointer()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()
    logger.info("saved checkpoint %s", path)
    if keep:
        _prune(ckpt_dir, keep)
    return path


def restore_checkpoint(ckpt_dir, target, step=None):
    """Restore the pytree saved at `step` (default: latest).

    `target` is an example pytree (same structure/shapes) — with sharded
    arrays, pass abstract shapes carrying shardings for direct-to-device
    restore.
    """
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            return None, None
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{int(step)}")
    restored = _checkpointer().restore(path, target)
    logger.info("restored checkpoint %s", path)
    return restored, step


def latest_step(ckpt_dir):
    """Largest step number with a checkpoint under ckpt_dir, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [int(m.group(1)) for d in os.listdir(ckpt_dir)
             if (m := _STEP_DIR.match(d))]
    return max(steps) if steps else None


def _prune(ckpt_dir, keep):
    import shutil
    steps = sorted(int(m.group(1)) for d in os.listdir(ckpt_dir)
                   if (m := _STEP_DIR.match(d)))
    for s in steps[:-keep]:
        shutil.rmtree(os.path.join(ckpt_dir, f"step_{s}"), ignore_errors=True)
        logger.info("pruned checkpoint step_%d", s)
