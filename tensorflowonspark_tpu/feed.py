"""User-side data feed & path utilities (maps reference TFNode.py:29-329).

`DataFeed` is the consumer half of InputMode.SPARK: the training process
pulls batches that feeder tasks pushed into the node's queue manager.  The
marker protocol is preserved from the reference (None = end of feed,
EndPartition = partition boundary), with one TPU-era change: records travel
in `marker.Chunk` batches, one queue item per chunk, because per-record
pickled queue puts are the reference's throughput ceiling (SURVEY.md §7).

`next_batch` returns records; `next_numpy_batch` stacks them into numpy
arrays ready for `jax.device_put`; `iter_batches` wraps the loop.
"""
import logging
from typing import Any, Iterable, Iterator, Optional

from . import marker
from . import shm as shm_mod

logger = logging.getLogger(__name__)


def device_prefetch(batch_iter: Iterable, sharding: Any = None,
                    depth: int = 2) -> Iterator:
    """Overlap host->HBM transfer with compute.

    Wraps an iterator of host batches (numpy pytrees) and yields
    device-resident batches while keeping up to `depth` transfers in
    flight ahead of the consumer.  JAX transfers are asynchronous —
    `device_put` returns immediately and the copy proceeds in the
    background — so steady-state throughput becomes max(compute,
    transfer) instead of compute+transfer.  This is the device half of
    the feed-throughput redesign (SURVEY.md §7: per-item queue reads were
    the reference's ceiling; `marker.PackedChunk` fixed the IPC half).

    `sharding=None` targets the default device; a NamedSharding (or a
    pytree of them matching the batch structure) routes through
    `parallel.mesh.put_batch`, which is multi-process aware.
    """
    import collections

    import jax

    from .parallel import mesh as mesh_mod

    def _put(batch):
        if sharding is None:
            return jax.device_put(batch)
        return mesh_mod.put_batch(batch, sharding)

    depth = max(1, int(depth))
    buf = collections.deque()
    for batch in batch_iter:
        buf.append(_put(batch))
        if len(buf) >= depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


def pad_batch(batch: Any, batch_size: int) -> Any:
    """Repeat-pad every array in a batch (array, tuple, or dict of arrays)
    along axis 0 up to `batch_size`; full batches pass through untouched."""
    import numpy as np

    def _pad(a):
        a = np.asarray(a)
        n = a.shape[0]
        if n >= batch_size:
            return a
        if n == 0:
            raise ValueError("cannot pad an empty batch (no row to repeat)")
        return np.concatenate([a, np.repeat(a[-1:], batch_size - n, axis=0)])

    if isinstance(batch, dict):
        return {k: _pad(v) for k, v in batch.items()}
    if isinstance(batch, tuple):
        return tuple(_pad(v) for v in batch)
    return _pad(batch)


def hdfs_path(ctx: Any, path: str) -> str:
    """Normalize a path per the filesystem schemes the cluster uses.

    Maps reference TFNode.hdfs_path (TFNode.py:29-64): absolute and
    scheme-qualified paths pass through; relative paths are resolved against
    the cluster's default FS (for remote schemes) or the node's working dir.
    """
    schemes = ("hdfs://", "viewfs://", "file://", "gs://", "s3://", "s3a://",
               "s3n://", "wasb://", "abfs://", "maprfs://", "oss://",
               "swift://", "memory://")  # memory:// = fsspec's in-memory FS
    # (all are openable through fsio/fsspec wherever a local path works)
    if path.startswith(schemes):
        return path
    local_fs = ctx.default_fs.startswith("file://") or not ctx.default_fs.startswith(schemes)
    if path.startswith("/"):
        return path if local_fs else ctx.default_fs + path
    if not local_fs:
        return f"{ctx.default_fs.rstrip('/')}/user/{ctx.user_name}/{path}"
    import os
    return os.path.join(ctx.working_dir, path)


class DataFeed:
    """Pulls feeder-pushed records from the node's input queue.

    Maps reference TFNode.DataFeed (TFNode.py:221-329); the public contract
    (`next_batch`, `should_stop`, `batch_results`, `terminate`) is identical.
    """

    def __init__(self, mgr, train_mode=True, qname_in="input", qname_out="output",
                 input_mapping=None):
        self.mgr = mgr
        self.train_mode = train_mode
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.input_mapping = input_mapping
        self.done_feeding = False
        # drained-but-unreturned records, as segments: ("rows", list) or a
        # PackedChunk kept COLUMNAR so next_numpy_batch never materializes
        # python row objects (the packed-transport fast path)
        self._segments = []
        self._partition_break = False
        self._progress = {}         # pid -> PUBLISHED delivered offset
        self._staged_progress = {}  # pid -> offset awaiting batch return
        self._ring = None
        self._ring_checked = False
        # queue proxies are cached: every mgr.get_queue() builds a fresh
        # AutoProxy over a fresh socket (several ms of server round trips)
        self._q_in = None
        self._q_out = None

    def _queue_in(self):
        if self._q_in is None:
            self._q_in = self.mgr.get_queue(self.qname_in)
        return self._q_in

    def _queue_out(self):
        if self._q_out is None:
            self._q_out = self.mgr.get_queue(self.qname_out)
        return self._q_out

    def _ring_handle(self):
        """Attach to the node's shm data plane on first use (the queue then
        carries ShmRefs whose payloads live in the ring)."""
        if not self._ring_checked:
            self._ring_checked = True
            try:
                info = shm_mod.discover(self.mgr)
                if info:
                    self._ring = shm_mod.attach_cached(info)
            except Exception:
                logger.warning("could not attach shm ring; expecting "
                               "queue-borne chunks", exc_info=True)
        return self._ring

    def _resolve_ref(self, ref):
        """ShmRef -> list of segments (PackedChunks / ("rows", list))."""
        ring = self._ring_handle()
        if ring is None:
            raise RuntimeError(
                "received a ShmRef but the node advertises no shm ring — "
                "feeder and consumer disagree about the data plane")
        payload = ring.read(ref)
        if isinstance(payload, shm_mod.MultiPayload):
            return [p if isinstance(p, marker.PackedChunk)
                    else ("rows", list(p)) for p in payload]
        if isinstance(payload, marker.PackedChunk):
            return [payload]
        return [("rows", list(payload))]

    @property
    def _buffer(self):
        """Pending record count (kept as the reference-era name)."""
        return sum(self._seg_len(s) for s in self._segments)

    @staticmethod
    def _seg_len(seg):
        return len(seg[1]) if isinstance(seg, tuple) else len(seg)

    def _take_blocks(self, batch_size, timeout=None):
        """Collect up to `batch_size` records as blocks (row lists or
        columnar PackedChunk slices), handling the marker protocol."""
        import queue as queue_mod

        # staged offsets from the PREVIOUS take are safe now: that batch
        # was returned to the training fn before this call
        if self._staged_progress:
            publish = False
            for pid, off in self._staged_progress.items():
                if off > self._progress.get(pid, 0):
                    self._progress[pid] = off
                    publish = True
            self._staged_progress = {}
            if publish:
                try:
                    self.mgr.set("feed_progress", dict(self._progress))
                except Exception:
                    logger.warning("could not publish feed progress",
                                   exc_info=True)

        q = self._queue_in()
        blocks, n = [], 0
        while n < batch_size:
            if self._segments:
                seg = self._segments[0]
                take = min(batch_size - n, self._seg_len(seg))
                if isinstance(seg, tuple):
                    rows = seg[1]
                    blocks.append(("rows", rows[:take]))
                    rest = rows[take:]
                    if rest:
                        self._segments[0] = ("rows", rest)
                    else:
                        self._segments.pop(0)
                else:  # PackedChunk: slice columns, stay columnar
                    blocks.append(("cols", marker.PackedChunk(
                        tuple(c[:take] for c in seg.columns), seg.row_type,
                        seg.matrix)))
                    if take < len(seg):
                        self._segments[0] = marker.PackedChunk(
                            tuple(c[take:] for c in seg.columns),
                            seg.row_type, seg.matrix)
                    else:
                        self._segments.pop(0)
                n += take
                continue
            if self.done_feeding or self._partition_break:
                break
            try:
                item = q.get(timeout=timeout) if timeout is not None else q.get()
            except queue_mod.Empty:
                break
            if item is None:
                self.done_feeding = True
                q.task_done()
            elif isinstance(item, marker.Progress):
                # DEFERRED high-water mark: records before this marker
                # have been drained into the current batch, but that
                # batch has not been RETURNED to the training fn yet — a
                # crash in that window must re-deliver them.  The offset
                # is staged here and published at the start of the NEXT
                # take (by which time the batch was handed out), so a
                # published offset never covers an undelivered record
                self._staged_progress[item.pid] = max(
                    self._staged_progress.get(item.pid, 0), item.offset)
                q.task_done()
            elif isinstance(item, marker.EndPartition):
                q.task_done()
                if n:
                    self._partition_break = True  # flush current batch first
                    break
                # nothing collected yet: partition boundary is invisible
            elif isinstance(item, shm_mod.ShmRef):
                self._segments.extend(self._resolve_ref(item))
                q.task_done()
            elif isinstance(item, marker.PackedChunk):
                self._segments.append(item)
                q.task_done()
            elif isinstance(item, marker.Chunk):
                self._segments.append(("rows", list(item.items)))
                q.task_done()
            elif blocks and blocks[-1][0] == "rows":
                # coalesce consecutive raw items into one rows block so the
                # numpy path stacks once instead of per record
                blocks[-1][1].append(item)
                n += 1
                q.task_done()
            else:
                blocks.append(("rows", [item]))
                n += 1
                q.task_done()
        if self._partition_break and not self._segments:
            self._partition_break = False
        return blocks

    @staticmethod
    def _rows_of(block):
        """Materialize a block into records.  Array-valued fields of packed
        field-records come back as numpy views (the values are identical;
        only list-vs-ndarray container type differs from what the feeder
        iterated)."""
        kind, data = block
        if kind == "rows":
            return data
        cols, row_type = data.columns, data.row_type
        if row_type is None:
            return list(cols[0])
        if row_type in (int, float, bool):
            # python-scalar records: tolist restores the exact scalar type
            return cols[0].tolist()
        if data.matrix:  # [N, F] matrix of flat rows: tolist is C-speed
            rows = cols[0].tolist()
            return rows if row_type is list else [row_type(r) for r in rows]
        return [row_type(c[i] for c in cols) for i in range(len(data))]

    def next_batch(self, batch_size: int,
                   timeout: Optional[float] = None) -> Any:
        """Return up to `batch_size` records.

        Returns fewer records at a partition boundary (so inference result
        accounting stays 1:1 per partition, reference: TFNode.py:243-288) and
        an empty/short batch at end-of-feed.  With `input_mapping` (a dict
        column_index_or_key -> name), returns {name: [values...]} instead.

        `timeout` (seconds) bounds each blocking wait: when no record
        arrives within `timeout`, returns whatever was collected so far
        (possibly []).
        Synchronous multi-worker consumers need this probe semantics — a
        worker blocked forever in q.get() while its peers sit in a gradient
        collective would deadlock the cluster (see
        parallel.train.feed_consensus); a bounded probe instead lets the
        worker vote "dry" and the cluster stop in lockstep.
        """
        batch = []
        for block in self._take_blocks(batch_size, timeout):
            batch.extend(self._rows_of(block))
        if self.input_mapping:
            return self._apply_mapping(batch)
        return batch

    def _apply_mapping(self, batch):
        cols = {name: [] for name in self.input_mapping.values()}
        for rec in batch:
            for key, name in self.input_mapping.items():
                cols[name].append(rec[key])
        return cols

    def next_numpy_batch(self, batch_size: int, dtype: Any = None,
                         timeout: Optional[float] = None) -> Any:
        """Like next_batch but stacks records into numpy arrays.

        Records that are tuples/lists of fields become a tuple of arrays
        (one per field); scalar/array records become one array; wide flat
        scalar records (feeder-packed as a matrix) become per-field column
        views.  This is the shape `jax.device_put` wants.  Feeder-packed
        chunks (marker.PackedChunk) pass through columnar — no python row
        objects are ever materialized on this path.  `timeout` bounds each
        blocking wait like next_batch's.
        """
        import numpy as np

        if self.input_mapping:
            batch = self.next_batch(batch_size, timeout=timeout)
            return {k: np.asarray(v, dtype=dtype) for k, v in batch.items()}

        blocks = self._take_blocks(batch_size, timeout)
        if not blocks:
            return None
        if all(kind == "cols" and data.matrix for kind, data in blocks):
            # wide flat records: concatenate the [N, F] matrices once and
            # expose per-field column views
            mats = [data.columns[0] for _, data in blocks]
            big = mats[0] if len(mats) == 1 else np.concatenate(mats)
            if dtype is not None:
                big = np.asarray(big, dtype=dtype)
            return tuple(big[:, i] for i in range(big.shape[1]))
        field_blocks = []   # per block: tuple of per-field arrays
        singles = []        # per block: records are single values (not field
        # tuples), so the result is one array instead of a tuple of arrays
        for kind, data in blocks:
            if kind == "cols":
                if data.matrix:
                    # mixed with non-matrix blocks (rare): expand to fields
                    mat = data.columns[0]
                    singles.append(False)
                    field_blocks.append(tuple(
                        mat[:, i] for i in range(mat.shape[1])))
                    continue
                singles.append(data.row_type not in (tuple, list))
                field_blocks.append(data.columns)
            else:
                first = data[0]
                if isinstance(first, (tuple, list)) and not np.isscalar(first):
                    singles.append(False)
                    field_blocks.append(tuple(
                        np.asarray([r[i] for r in data])
                        for i in range(len(first))))
                else:
                    singles.append(True)
                    field_blocks.append((np.asarray(data),))
        nf = len(field_blocks[0])
        if (any(len(fb) != nf for fb in field_blocks)
                or any(s != singles[0] for s in singles)):
            raise ValueError("inconsistent record shapes across feed chunks")
        fields = tuple(
            np.asarray(np.concatenate([fb[i] for fb in field_blocks])
                       if len(field_blocks) > 1 else field_blocks[0][i],
                       dtype=dtype)
            for i in range(nf))
        return fields[0] if singles[0] else fields

    @staticmethod
    def _is_empty(batch):
        """Recognize an empty batch in every shape next_batch can return:
        None, [], {}, a mapping of empty columns, a tuple of empty arrays,
        or a zero-length array."""
        if batch is None:
            return True
        if isinstance(batch, dict):
            return all(len(v) == 0 for v in batch.values()) or not batch
        if isinstance(batch, tuple):
            return all(len(v) == 0 for v in batch) or not batch
        return hasattr(batch, "__len__") and len(batch) == 0

    def iter_batches(self, batch_size: int,
                     numpy: bool = False) -> Iterator:
        """Generator over batches until end-of-feed."""
        while not self.should_stop():
            batch = (self.next_numpy_batch(batch_size) if numpy
                     else self.next_batch(batch_size))
            if self._is_empty(batch):
                if self.should_stop():
                    break
                continue
            yield batch

    def iter_device_batches(self, batch_size, sharding=None, depth=2,
                            pad=None):
        """Generator over device-resident batches with `depth` host->HBM
        transfers kept in flight (see `device_prefetch`).

        `pad` repeat-pads ragged tail batches (end-of-feed / partition
        boundaries) up to `batch_size` so the jitted step keeps one
        static shape.  Defaults to True when `sharding` is given — a
        short tail cannot tile over a dp>1 mesh.

        NOTE (multi-process SPMD): padding fixes ragged *shapes* only.
        When per-process feeds can yield different batch *counts*, a
        process that exhausts its feed early leaves its peers blocked in
        the step collective — that case needs a bounded-probe loop with
        `parallel.train.feed_consensus` voting each step (see
        examples/mnist/mnist_common.py), not this generator.
        """
        if pad is None:
            pad = sharding is not None
        batches = self.iter_batches(batch_size, numpy=True)
        if pad:
            batches = (pad_batch(b, batch_size) for b in batches)
        return device_prefetch(batches, sharding=sharding, depth=depth)

    def should_stop(self):
        """True once the end-of-feed sentinel was consumed (reference: TFNode.py:290)."""
        return self.done_feeding and not self._buffer

    def batch_results(self, results):
        """Push inference results to the output queue (reference: TFNode.py:294-305)."""
        q = self._queue_out()
        for item in results:
            q.put(item)

    def terminate(self):
        """Signal feeders to stop and drain the input queue (reference: TFNode.py:307-329)."""
        logger.info("terminate() requested; marking state terminating")
        self.mgr.set("state", "terminating")
        # Drain whatever is in flight so feeder queue.join() can complete.
        q = self._queue_in()
        import queue as queue_mod
        count = 0
        done = False
        while not done:
            try:
                item = q.get(timeout=3)
                if isinstance(item, shm_mod.ShmRef):
                    # free the ring frames so a feeder blocked on a full
                    # ring unblocks and sees the 'terminating' state
                    ring = self._ring_handle()
                    if ring is not None:
                        ring.skip(item)
                q.task_done()
                count += 1
                if item is None:
                    self.done_feeding = True
            except queue_mod.Empty:
                done = True
            except (OSError, EOFError, BrokenPipeError) as e:
                # the manager is already gone (cluster shutdown won the
                # race): nothing left to drain, feeders are dead too
                logger.info("terminate(): manager closed mid-drain (%s)", e)
                self.done_feeding = True
                done = True
        logger.info("terminate() drained %d in-flight items", count)
