"""Evaluation metrics — jit-friendly functions + a device-side accumulator.

The reference delegates metrics to Keras ``model.compile(metrics=...)``
(reference: examples/mnist/keras/mnist_spark.py:45-49 compiles accuracy;
the estimator examples use ``tf.metrics``).  Here the framework owns them:
pure functions over (logits, labels) that run inside jit (so eval stays on
the MXU/VPU, sharded like the forward pass), and `MetricAccumulator` which
keeps running sums AS DEVICE SCALARS — accumulation composes with async
dispatch and the final `result()` is the only host readback.

All functions accept an optional boolean/0-1 `mask` (padding-aware eval,
e.g. repeat-padded tail batches from `feed.pad_batch`: mask off the
duplicated rows so they don't bias the metric).

Also here: :class:`Counters`, host-side thread-safe monotone counters for
the serving/orchestration plane (the fleet gateway's ejection/retry/429
accounting), :class:`Gauge`, a level gauge with a high-water mark (the
async decode engine's pipeline depth), and :class:`LatencyWindow`, the
serving-latency tracker
(TTFT percentiles + fleet-summable count/sum).  JAX is imported lazily
inside the eval functions so
importing this module from a pure control-plane process (the gateway)
never pays accelerator-runtime startup — the same discipline as `util`.

The scrape surface lives here too: :func:`prometheus_text` renders the
flat ``stats()`` dicts the serving plane already produces into
Prometheus text exposition (gauges for numeric keys, ``_bucket``/
``_sum``/``_count`` triplets for :meth:`LatencyWindow.histogram`
dicts), so ``GET /metrics`` on replica and gateway is generated, not
hand-maintained.  New engine stats keys are therefore exported
automatically — e.g. the speculative-decoding counters
(``spec_rounds``/``spec_tokens_proposed``/``spec_tokens_accepted``/
``spec_accept_rate``/``spec_draft_fallbacks``) appear on ``/metrics``
with no exporter change.
"""
import bisect
import threading


def _masked_mean(values, mask):
    import jax.numpy as jnp

    values = values.astype(jnp.float32)
    if mask is None:
        return values.mean(), values.size * jnp.ones((), jnp.float32)
    m = mask.astype(jnp.float32).reshape(values.shape)
    n = jnp.maximum(m.sum(), 1.0)
    return (values * m).sum() / n, m.sum()


def accuracy(logits, labels, mask=None):
    """Top-1 accuracy over [..., num_classes] logits."""
    import jax.numpy as jnp

    hit = (jnp.argmax(logits, axis=-1) == labels)
    return _masked_mean(hit, mask)[0]


def topk_accuracy(logits, labels, k=5, mask=None):
    """Top-k accuracy: label within the k highest logits."""
    import jax.numpy as jnp

    topk = jnp.argsort(logits, axis=-1)[..., -k:]
    hit = (topk == labels[..., None]).any(axis=-1)
    return _masked_mean(hit, mask)[0]


def cross_entropy(logits, labels, mask=None):
    """Mean softmax cross entropy with integer labels (f32 accumulators)."""
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None],
                               axis=-1)[..., 0]
    return _masked_mean(logz - gold, mask)[0]


def perplexity(logits, labels, mask=None):
    """exp(mean token cross entropy) — LM eval."""
    import jax.numpy as jnp

    return jnp.exp(cross_entropy(logits, labels, mask))


def mean_squared_error(pred, target, mask=None):
    import jax.numpy as jnp

    return _masked_mean((pred.astype(jnp.float32)
                         - target.astype(jnp.float32)) ** 2, mask)[0]


def confusion_matrix(preds, labels, num_classes, mask=None):
    """[num_classes, num_classes] float32 counts, rows = true class.

    One-hot matmul formulation: a [N, C] x [N, C] contraction the MXU
    executes directly — no scatter, no sort, jit/SPMD-friendly (a
    per-shard matrix psums cleanly across data-parallel shards).
    """
    import jax
    import jax.numpy as jnp

    preds = preds.reshape(-1)
    labels = labels.reshape(-1)
    t = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    p = jax.nn.one_hot(preds, num_classes, dtype=jnp.float32)
    if mask is not None:
        t = t * mask.astype(jnp.float32).reshape(-1, 1)
    return t.T @ p


def mean_iou(logits, labels, mask=None, num_classes=None):
    """Mean intersection-over-union — the canonical segmentation metric
    (pairs with models.unet / models.deeplab; the reference's
    segmentation examples track only pixel accuracy).

    IoU_c = TP_c / (TP_c + FP_c + FN_c), averaged over classes that
    APPEAR (in labels or predictions — absent classes don't dilute the
    mean).  ``mask`` excludes ignore pixels.  Returns a scalar; for
    multi-batch eval accumulate `confusion_matrix` per batch and call
    `iou_from_confusion` once.
    """
    import jax.numpy as jnp

    num_classes = num_classes or logits.shape[-1]
    cm = confusion_matrix(jnp.argmax(logits, axis=-1), labels,
                          num_classes, mask)
    return iou_from_confusion(cm)


def iou_from_confusion(cm):
    """Mean IoU from an accumulated confusion matrix (rows = true)."""
    import jax.numpy as jnp

    cm = cm.astype(jnp.float32)
    tp = jnp.diagonal(cm)
    fn = cm.sum(axis=1) - tp
    fp = cm.sum(axis=0) - tp
    denom = tp + fp + fn
    present = denom > 0
    iou = jnp.where(present, tp / jnp.maximum(denom, 1.0), 0.0)
    return iou.sum() / jnp.maximum(present.sum(), 1)


class MetricAccumulator:
    """Running weighted means kept on device until `result()`.

    Usage (inside an eval loop over batches)::

        acc = MetricAccumulator()
        for batch in ds:
            logits = eval_step(params, batch)      # jitted
            acc.update(n=labels.size,
                       accuracy=metrics.accuracy(logits, labels),
                       loss=metrics.cross_entropy(logits, labels))
        print(acc.result())                        # ONE host readback

    `update` values AND the weight `n` may be device scalars (preferred —
    nothing syncs until `result()`) or plain numbers; `n` weights the
    batch (defaults to 1 per update).  With masked metrics, pass the
    VALID count as the weight so padding rows don't bias the aggregate::

        n = mask.sum() if mask is not None else labels.size   # device scalar
        acc.update(n=n, accuracy=metrics.accuracy(logits, labels, mask))
    """

    def __init__(self):
        self._sums = {}
        self._weights = {}

    def update(self, n=1, **values):
        for tag, v in values.items():
            prev_s, prev_w = self._sums.get(tag), self._weights.get(tag)
            s = v * n
            self._sums[tag] = s if prev_s is None else prev_s + s
            self._weights[tag] = n if prev_w is None else prev_w + n

    def result(self):
        """{tag: float} — the only device->host sync."""
        import numpy as np
        return {tag: float(np.asarray(s)) / float(np.asarray(self._weights[tag]))
                for tag, s in self._sums.items()}


class Counters:
    """Thread-safe named monotone counters for the host-side serving /
    orchestration plane (no JAX involved).

    The fleet gateway accounts its routing decisions here — ejections,
    re-admissions, hedged retries, 429 rejections, prefix-affinity hits
    and spills — and `GET /v1/fleet` surfaces `snapshot()` verbatim, so
    every unhappy-path transition is observable.  Unknown names read as
    0: dashboards can reference a counter before its first event."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}

    def inc(self, name, n=1):
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n
            return self._counts[name]

    def get(self, name):
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self):
        """{name: count} copy, safe to serialize."""
        with self._lock:
            return dict(self._counts)


class Gauge:
    """Thread-safe level gauge with a high-water mark (no JAX): tracks a
    current value that goes up AND down (unlike :class:`Counters`) plus
    the peak it ever reached.  The async decode engine uses one for
    pipeline depth — steps dispatched but not yet host-processed — where
    ``peak`` is the observable proof the double buffer actually kept >1
    step in flight."""

    def __init__(self, value=0):
        self._lock = threading.Lock()
        self._value = value
        self._peak = value

    def add(self, n=1):
        with self._lock:
            self._value += n
            if self._value > self._peak:
                self._peak = self._value
            return self._value

    def set(self, value):
        with self._lock:
            self._value = value
            if value > self._peak:
                self._peak = value

    @property
    def value(self):
        with self._lock:
            return self._value

    @property
    def peak(self):
        with self._lock:
            return self._peak


class LatencyWindow:
    """Thread-safe latency tracker for the serving plane (no JAX): a
    bounded window of recent samples for percentiles plus MONOTONE
    count/sum that never resets — the fleet gateway aggregates the
    monotone pair across replicas (percentiles don't sum; averages of
    sums do).  Used for admission->first-token (TTFT) in the
    continuous batcher.  Reads before the first sample return zeros so
    dashboards can reference the keys unconditionally."""

    # Fixed bucket upper bounds (ms), shared by every LatencyWindow so
    # per-replica histograms merge by elementwise sum at the gateway —
    # the summable replacement for the window percentiles, which
    # deliberately never aggregate across replicas.
    BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                  500.0, 1000.0, 2500.0, 5000.0, 10000.0)

    def __init__(self, window=512):
        self._lock = threading.Lock()
        self._recent = []          # bounded ring of recent samples (ms)
        self._window = max(1, int(window))
        self._count = 0            # monotone, fleet-aggregable
        self._sum_ms = 0.0
        # per-bucket (non-cumulative) counts; index len(BUCKETS_MS) is
        # the +Inf overflow bucket
        self._bucket_counts = [0] * (len(self.BUCKETS_MS) + 1)

    def record(self, seconds):
        ms = float(seconds) * 1000.0
        with self._lock:
            self._count += 1
            self._sum_ms += ms
            i = bisect.bisect_left(self.BUCKETS_MS, ms)
            self._bucket_counts[i] += 1
            self._recent.append(ms)
            if len(self._recent) > self._window:
                del self._recent[:len(self._recent) - self._window]

    def histogram(self):
        """Prometheus-style cumulative histogram: ``le`` upper bounds
        (``"+Inf"`` last), cumulative ``counts``, monotone ``count`` /
        ``sum_ms``.  Merge replicas with :meth:`merge_histograms`."""
        with self._lock:
            counts, total = list(self._bucket_counts), self._sum_ms
            n = self._count
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return {"le": [*self.BUCKETS_MS, "+Inf"], "counts": cum,
                "count": n, "sum_ms": round(total, 3)}

    @staticmethod
    def merge_histograms(hists):
        """Elementwise-sum histograms from :meth:`histogram` (same
        bucket layout); entries with a foreign layout are skipped."""
        out = None
        for h in hists:
            if not (isinstance(h, dict) and isinstance(h.get("le"), list)
                    and isinstance(h.get("counts"), list)
                    and len(h["le"]) == len(h["counts"])):
                continue
            if out is None:
                out = {"le": list(h["le"]),
                       "counts": list(h["counts"]),
                       "count": int(h.get("count", 0)),
                       "sum_ms": float(h.get("sum_ms", 0.0))}
                continue
            if h["le"] != out["le"]:
                continue
            out["counts"] = [a + b for a, b in
                             zip(out["counts"], h["counts"])]
            out["count"] += int(h.get("count", 0))
            out["sum_ms"] += float(h.get("sum_ms", 0.0))
        if out is not None:
            out["sum_ms"] = round(out["sum_ms"], 3)
        return out

    @staticmethod
    def quantile_from_histogram(hist, q):
        """histogram_quantile-style estimate: linear interpolation
        inside the bucket holding rank ``q``; the overflow bucket
        reports its lower bound (same convention as Prometheus)."""
        if not hist or not hist.get("counts"):
            return 0.0
        cum, les = hist["counts"], hist["le"]
        total = cum[-1]
        if total <= 0:
            return 0.0
        rank = q * total
        prev_cum = 0
        for i, c in enumerate(cum):
            if c >= rank:
                lo = 0.0 if i == 0 else float(les[i - 1])
                if les[i] == "+Inf":
                    return round(lo, 3)
                hi = float(les[i])
                in_bucket = c - prev_cum
                frac = ((rank - prev_cum) / in_bucket) if in_bucket else 1.0
                return round(lo + (hi - lo) * frac, 3)
            prev_cum = c
        return round(float(les[-2]) if len(les) > 1 else 0.0, 3)

    @staticmethod
    def _percentile(sorted_ms, q):
        if not sorted_ms:
            return 0.0
        # nearest-rank on the window: exact for the small-N serving case,
        # no interpolation surprises at the extremes
        i = int(round(q * (len(sorted_ms) - 1)))
        return sorted_ms[min(len(sorted_ms) - 1, i)]

    def stats(self, prefix):
        """{prefix}_count / _ms_sum (monotone, summable across replicas)
        + _avg_ms / _p50_ms / _p95_ms (window-local) + _hist (the
        fixed-bucket cumulative histogram, summable across replicas)."""
        with self._lock:
            count, total = self._count, self._sum_ms
            recent = sorted(self._recent)
        return {
            f"{prefix}_count": count,
            f"{prefix}_ms_sum": round(total, 3),
            f"{prefix}_avg_ms": round(total / count, 3) if count else 0.0,
            f"{prefix}_p50_ms": round(self._percentile(recent, 0.50), 3),
            f"{prefix}_p95_ms": round(self._percentile(recent, 0.95), 3),
            f"{prefix}_hist": self.histogram(),
        }


def _prom_name(name):
    """Sanitize a stats key into a Prometheus metric name."""
    out = []
    for ch in str(name):
        out.append(ch if (ch.isascii() and (ch.isalnum() or ch == "_"))
                   else "_")
    s = "".join(out)
    return ("_" + s) if s[:1].isdigit() else (s or "_")


def _prom_labels(labels):
    if not labels:
        return ""
    body = ",".join(
        '%s="%s"' % (_prom_name(k),
                     str(v).replace("\\", r"\\").replace('"', r'\"'))
        for k, v in sorted(labels.items()))
    return "{%s}" % body


def _prom_value(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def prometheus_text(groups, namespace="tfospark"):
    """Render ``[(subsystem, labels, stats_dict), ...]`` into the
    Prometheus text exposition format (version 0.0.4).

    Numeric values become gauges; dicts shaped like
    :meth:`LatencyWindow.histogram` become ``_bucket``/``_sum``/
    ``_count`` histogram triplets; strings/lists/None are skipped.
    ``# TYPE`` headers are emitted once per metric name even when the
    same name repeats with different labels (per-replica export)."""
    lines = []
    typed = set()

    def emit_type(full, kind):
        if full not in typed:
            typed.add(full)
            lines.append(f"# TYPE {full} {kind}")

    for subsystem, labels, stats in groups:
        base = namespace + ("_" + _prom_name(subsystem)
                            if subsystem else "")
        lab = _prom_labels(labels)
        for key in sorted(stats or {}):
            val = stats[key]
            full = f"{base}_{_prom_name(key)}"
            if isinstance(val, dict):
                if not (isinstance(val.get("le"), list)
                        and isinstance(val.get("counts"), list)):
                    continue
                stem = full[:-5] if full.endswith("_hist") else full
                emit_type(stem, "histogram")
                for le, c in zip(val["le"], val["counts"]):
                    le_lab = dict(labels or {})
                    le_lab["le"] = le
                    lines.append(f"{stem}_bucket{_prom_labels(le_lab)}"
                                 f" {c}")
                lines.append(f"{stem}_sum{lab}"
                             f" {_prom_value(float(val.get('sum_ms', 0.0)))}")
                lines.append(f"{stem}_count{lab}"
                             f" {int(val.get('count', 0))}")
                continue
            if isinstance(val, (int, float)):
                emit_type(full, "gauge")
                lines.append(f"{full}{lab} {_prom_value(val)}")
    return "\n".join(lines) + "\n"

