"""Request-scoped distributed tracing for the serving stack.

A trace ID is minted (or accepted via ``X-Trace-Id``) at the fleet
gateway, forwarded in the replica-bound body exactly like priority
classes (``fleet.py``), carried inside the batcher's pending item, and
— for the exotic hops — inside the wire-snapshot meta (migration,
park/unpark) and the journal replay meta, so one request keeps one ID
across every process that ever touches it.

Each process holds a :class:`Recorder`: a bounded ring of completed
spans stamped with the host monotonic clock.  Nothing here ever reads
a device value — decode-tick spans are recorded from the host drain
thread (``_host_loop``) at token-commit time, so the async engine
stays hostsync-clean.  The ring is a ``collections.deque(maxlen=...)``:
recording is O(1), old spans fall off the back, and a wedged or
fault-injected exporter can never apply backpressure to serving
(``faults.deny("trace.export")`` makes the recorder drop spans
silently — streams must stay byte-identical).

Span shape (JSON-ready)::

    {"trace": "4f2a…", "name": "prefill", "t0_ms": 12.3,
     "t1_ms": 14.9, "dur_ms": 2.6, "attrs": {"row": 3, "chunk": 256}}

``t0_ms``/``t1_ms`` are ``time.monotonic()`` milliseconds — comparable
within one process only; the gateway's ``GET /v1/trace/<id>`` stitches
per-process timelines side by side (tagged with their source) rather
than pretending clocks align.

Lifecycle discipline: a span handed out by :meth:`Recorder.begin` must
reach exactly one of :meth:`Recorder.end` / :meth:`Recorder.abandon`
(the ``trace-span`` graftcheck ResourceSpec enforces this statically).
Sites that cannot scope a span inside one function use
:meth:`Recorder.span_at` with explicit endpoints instead — nothing
open ever escapes.
"""
import collections
import contextlib
import threading
import time
import uuid

from . import faults

# Hex digits plus dashes: accepts both uuid4().hex and W3C-style
# dashed trace ids from external callers.  Anything else is rejected
# at the door (gateway mints a fresh id; replica _validate 400s).
_ID_CHARS = frozenset("0123456789abcdefABCDEF-")
MAX_ID_LEN = 64

# Stage names recorded by the stack, for reference and docs:
#   gateway.route  gateway.relay  gateway.replay
#   queue  admit  prefill  decode  retire
#   freeze  wire  resume  replay  park  unpark
#   promote  prefix_pull
#   job.submit  job.partition  job.record  job.cancel  job.done
DEFAULT_RING = 4096
DEFAULT_DECODE_SAMPLE = 16


def new_id():
    """A fresh 32-hex-char trace id."""
    return uuid.uuid4().hex


def valid_id(tid):
    """True for a plausible externally-supplied trace id."""
    return (isinstance(tid, str) and 0 < len(tid) <= MAX_ID_LEN
            and not set(tid) - _ID_CHARS)


def _now_ms():
    return time.monotonic() * 1000.0


class Recorder:
    """Bounded per-process span ring.

    Every method tolerates ``trace_id=None`` (untraced request) by
    doing nothing and returning ``None`` — call sites never branch on
    whether tracing is on, which keeps the traced and untraced code
    paths literally the same instructions apart from dict stores.
    """

    def __init__(self, capacity=DEFAULT_RING,
                 decode_sample=DEFAULT_DECODE_SAMPLE):
        self.capacity = int(capacity) if capacity else DEFAULT_RING
        # every Nth committed host tick per traced row gets a decode
        # span; 0/None disables decode sampling entirely
        self.decode_sample = int(decode_sample or 0)
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=self.capacity)
        self.recorded = 0       # spans accepted into the ring
        self.dropped = 0        # spans dropped by the export fault site

    # -- recording ----------------------------------------------------

    def begin(self, trace_id, name, **attrs):
        """Open a span; returns the span token (or None when
        untraced).  Must be balanced by end()/abandon()."""
        if not trace_id:
            return None
        return {"trace": trace_id, "name": name, "t0_ms": _now_ms(),
                "attrs": attrs}

    def end(self, span, **attrs):
        """Close and record a span from begin()."""
        if span is None:
            return
        span["t1_ms"] = _now_ms()
        if attrs:
            span["attrs"].update(attrs)
        self._push(span)

    def abandon(self, span):
        """Close a span whose operation failed; recorded with an
        ``abandoned`` marker so the timeline shows the cut."""
        if span is None:
            return
        span["attrs"]["abandoned"] = True
        span["t1_ms"] = _now_ms()
        self._push(span)

    def event(self, trace_id, name, **attrs):
        """A zero-duration span (point event)."""
        if not trace_id:
            return
        t = _now_ms()
        self._push({"trace": trace_id, "name": name, "t0_ms": t,
                    "t1_ms": t, "attrs": attrs})

    def span_at(self, trace_id, name, t0, t1, **attrs):
        """Record a completed span with explicit monotonic endpoints
        (seconds, as from ``time.monotonic()``) — for stages whose
        start was stamped in another function/thread."""
        if not trace_id:
            return
        self._push({"trace": trace_id, "name": name,
                    "t0_ms": t0 * 1000.0, "t1_ms": t1 * 1000.0,
                    "attrs": attrs})

    @contextlib.contextmanager
    def span(self, trace_id, name, **attrs):
        """Context manager for spans scoped to one block; failures
        inside the block record the span with ``abandoned`` set."""
        s = self.begin(trace_id, name, **attrs)
        try:
            yield s
        except BaseException:
            self.abandon(s)
            raise
        self.end(s)

    def _push(self, span):
        span["dur_ms"] = round(span["t1_ms"] - span["t0_ms"], 3)
        span["t0_ms"] = round(span["t0_ms"], 3)
        span["t1_ms"] = round(span["t1_ms"], 3)
        if faults.deny("trace.export"):
            # chaos site: the observability plane "failing" must cost
            # spans, never tokens — drop silently and count it
            with self._lock:
                self.dropped += 1
            return
        with self._lock:
            self._ring.append(span)
            self.recorded += 1

    # -- querying -----------------------------------------------------

    def spans(self, trace_id):
        """All retained spans for a trace id, oldest first."""
        with self._lock:
            return [dict(s) for s in self._ring
                    if s["trace"] == trace_id]

    def summary(self, trace_id):
        """Compact per-request digest for the final stream event:
        span count and per-stage {count, total ms}."""
        found = self.spans(trace_id)
        if not found:
            return None
        stages = {}
        for s in found:
            st = stages.setdefault(s["name"], {"count": 0, "ms": 0.0})
            st["count"] += 1
            st["ms"] = round(st["ms"] + s["dur_ms"], 3)
        return {"id": trace_id, "spans": len(found), "stages": stages}

    def stats(self):
        with self._lock:
            return {"trace_spans_recorded": self.recorded,
                    "trace_spans_dropped": self.dropped,
                    "trace_ring_len": len(self._ring),
                    "trace_ring_capacity": self.capacity}
