"""TPU-first input pipeline: the tf.data equivalent for InputMode.NATIVE.

The reference's file-fed path delegates shard/shuffle/repeat/batch/prefetch
to ``tf.data`` inside the user map_fun (reference:
examples/mnist/keras/mnist_tf_ds.py:41-50 — ``ds.shard(num_workers,
worker_index).shuffle(...).batch(...)``; examples/mnist/keras/mnist_tf.py).
This framework owns that pipeline instead: a lazy, re-iterable `Dataset`
over TFRecord shards (or any record source) whose terminal stage hands
device-resident, mesh-sharded batches to the jitted train step via
`feed.device_prefetch`.

Design points (TPU-first):
- **file-granular sharding** before any IO: each process opens only its own
  shards (`shard(n, i)`), the multi-host analog of ``ds.shard``;
- **windowed shuffle** with a fixed-size buffer and a per-epoch seed —
  streaming, O(buffer) memory, deterministic under a fixed seed like
  ``tf.data.Dataset.shuffle``;
- **static batch shapes**: `batch(..., drop_remainder=True)` is the default
  for training so the jitted step never recompiles; the ragged tail can
  instead be repeat-padded (`pad_tail=True`) to keep every record;
- **device prefetch** as the terminal stage: N host->HBM transfers kept in
  flight (max(compute, transfer) steady state, SURVEY.md §7).

Example::

    ds = (data.Dataset.from_tfrecords(glob_pattern)
              .shard(ctx.num_processes, ctx.process_id)
              .map(parse)
              .shuffle(4096, seed=epoch)
              .repeat(epochs)
              .batch(512, drop_remainder=True))
    for batch in ds.prefetch_to_device(sharding):
        state, metrics = step(state, batch, rng)
"""
import glob as glob_mod
import logging
import random

logger = logging.getLogger(__name__)


class Dataset:
    """Lazy, composable, re-iterable record pipeline.

    Every transformation returns a NEW Dataset; iterating builds a fresh
    generator chain, so one Dataset can be iterated many times (each
    `repeat`/`shuffle` epoch reseeds deterministically from its base seed).
    """

    def __init__(self, source, parent=None, op=None):
        # source: () -> iterator of records (only for root datasets)
        self._source = source
        self._parent = parent
        self._op = op or (lambda it: it)

    # ---------------------------------------------------------------- roots

    @classmethod
    def from_records(cls, records):
        """Root dataset over an in-memory sequence (list of tuples/dicts)."""
        return cls(lambda: iter(records))

    @classmethod
    def from_generator(cls, gen_fn):
        """Root dataset over `gen_fn() -> iterator` (fresh per iteration)."""
        return cls(gen_fn)

    @classmethod
    def from_files(cls, paths, reader):
        """Root over files: `reader(path) -> iterator of records`.

        `paths` may be a glob pattern, a list, or a directory.  File order
        is sorted for determinism; `shard()` before iteration splits at
        file granularity when possible.
        """
        ds = cls(None)
        ds._files = _expand_paths(paths)
        ds._reader = reader
        ds._shard_spec = None
        ds._source = ds._file_source
        return ds

    @classmethod
    def from_tfrecords(cls, paths, parse=None):
        """Root over TFRecord shards of `tf.train.Example` records.

        Records arrive as `{name: (kind, values)}` dicts (tfrecord module
        decode format); `parse` maps each decoded example (e.g. to a
        (features, label) tuple).  Maps the reference's
        ``TFRecordDataset -> parse_fn`` idiom (mnist_tf_ds.py:41-50).
        """
        from . import tfrecord

        def reader(path):
            it = tfrecord.read_examples(path)
            return (parse(ex) for ex in it) if parse else it

        return cls.from_files(paths, reader)

    @classmethod
    def from_indexed_tfrecords(cls, paths, parse=None, global_shuffle=False,
                               seed=0, shuffle_block=1, verify_crc=True):
        """Root over indexed TFRecord shards with RANDOM access
        (tfrecord.IndexedTFRecordFile; sidecar indexes are used when
        present and built in memory otherwise).

        This is the ArrayRecord-style input path (SURVEY.md §2.2).  Where
        `from_tfrecords` reads shards sequentially (so `shuffle(buffer)`
        only ever mixes records ~buffer apart and `shard()` is
        file-granular), this root addresses every (file, record)
        coordinate directly:

        - ``global_shuffle=True`` draws a fresh uniform permutation of ALL
          records each epoch (`seed` + epoch index, the `shuffle()` reseed
          convention) — exact global shuffle, O(index) memory;
        - ``shard(n, i)`` slices the (permuted) coordinate list, giving
          every worker a disjoint, balanced 1/n of the records regardless
          of file count or file sizes — record-granular, and each worker
          reads ONLY its own records (no scan-and-discard);
        - ``shuffle_block=k`` permutes blocks of k consecutive records
          instead of single records: each block is fetched with one ranged
          read, trading perfect uniformity for sequential IO (the
          ArrayRecord shuffle-granularity tradeoff; k=1 is exact).
        """
        if shuffle_block < 1:
            raise ValueError("shuffle_block must be >= 1")
        cfg = {"parse": parse, "global_shuffle": bool(global_shuffle),
               "seed": int(seed), "block": int(shuffle_block),
               "verify": verify_crc}
        return cls._indexed_root(_expand_paths(paths), cfg, None)

    @classmethod
    def from_tfrecord_columns(cls, paths, features, batch_size,
                              drop_remainder=True, shuffle=False, seed=0):
        """Root of COLUMNAR batches over fixed-schema numeric TFRecord
        shards — the native fast path for dense training data (MNIST-like:
        a float feature + an int64 label).

        Each shard is decoded with one native C pass per feature
        (:func:`tensorflowonspark_tpu.tfrecord.read_column`, ~10x the
        record codec) and batches are SLICES of the shard columns —
        individual records never exist as Python objects.  Yields
        ``{name: array[batch_size, feat_len]}`` dicts; remainders carry
        across shard boundaries, so batch shapes are static everywhere
        except an optional final partial batch (``drop_remainder=False``).

        ``shuffle=True`` permutes records within each shard per epoch
        (``seed`` + epoch, the shuffle() reseed convention).  ``shard()``
        slices the file list (call before iteration); downstream
        ``map``/``prefetch``/``prefetch_to_device`` compose per batch.
        """
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if not features:
            raise ValueError("features must name at least one column")
        cfg = {"features": tuple(features), "batch": int(batch_size),
               "drop": bool(drop_remainder), "shuffle": bool(shuffle),
               "seed": int(seed)}
        return cls._columnar_root(_expand_paths(paths), cfg, None)

    @classmethod
    def _columnar_root(cls, files, cfg, shard_spec):
        ds = cls(None)
        ds._files = files
        ds._columnar = cfg
        ds._shard_spec = shard_spec
        ds._epoch_source = ds._columnar_iter
        return ds

    def _columnar_iter(self, epoch):
        import numpy as np

        from . import tfrecord

        files = self._files
        if self._shard_spec:
            n, i = self._shard_spec
            files = files[i::n]
        if not files:
            raise ValueError("dataset matched no input files")
        cfg = self._columnar
        B = cfg["batch"]
        pending = None                   # {name: [rows...]} leftover columns

        def _concat(a, b):
            return b if a is None else {
                k: np.concatenate([a[k], b[k]]) for k in b}

        for fi, path in enumerate(files):
            if next(tfrecord.read_examples(path), None) is None:
                continue                     # valid zero-record shard
            cols = {name: tfrecord.read_column(path, name)
                    for name in cfg["features"]}
            n_rec = len(next(iter(cols.values())))
            for name, c in cols.items():
                if len(c) != n_rec:
                    raise IOError(f"{path}: feature {name!r} has "
                                  f"{len(c)} records, expected {n_rec}")
            if cfg["shuffle"]:
                # stable per-(seed, epoch, file) stream — NOT hash(),
                # which is salted per process
                rng = np.random.default_rng(
                    (cfg["seed"] * 1_000_003 + epoch
                     + fi * 2_654_435_761) % (2 ** 63))
                perm = rng.permutation(n_rec)
                cols = {k: c[perm] for k, c in cols.items()}
            cols = _concat(pending, cols)
            n_rec = len(next(iter(cols.values())))
            n_full = n_rec // B
            for j in range(n_full):
                yield {k: c[j * B:(j + 1) * B] for k, c in cols.items()}
            pending = ({k: c[n_full * B:] for k, c in cols.items()}
                       if n_rec % B else None)
        if pending is not None and not cfg["drop"]:
            yield pending

    @classmethod
    def _indexed_root(cls, files, cfg, shard_spec):
        ds = cls(None)
        ds._files = files
        ds._indexed = cfg
        ds._shard_spec = shard_spec
        ds._epoch_source = ds._indexed_iter
        return ds

    # At most this many shard files keep an open fd during indexed
    # iteration; the rest are release()d LRU and reopen on demand.
    _MAX_OPEN_READERS = 128

    def _indexed_readers(self):
        from . import tfrecord

        readers = getattr(self, "_idx_readers", None)
        if readers is None:
            readers = [tfrecord.IndexedTFRecordFile(
                p, verify_crc=self._indexed["verify"]) for p in self._files]
            self._idx_readers = readers
        return readers

    def _indexed_iter(self, epoch):
        import collections

        from . import tfrecord

        if not self._files:
            raise ValueError("dataset matched no input files")
        cfg = self._indexed
        readers = self._indexed_readers()
        block = cfg["block"]
        coords = []                      # (file_idx, start_record, n_records)
        for fi, r in enumerate(readers):
            n = len(r)
            coords.extend((fi, s, min(block, n - s))
                          for s in range(0, n, block))
        if cfg["global_shuffle"]:
            # same reseed scheme as shuffle(): deterministic per (seed,
            # epoch), identical on every worker so shard slices stay
            # disjoint across processes
            rng = random.Random(cfg["seed"] * 1_000_003 + epoch)
            rng.shuffle(coords)
        if self._shard_spec:
            n_shards, idx = self._shard_spec
            coords = coords[idx::n_shards]
        parse = cfg["parse"]
        open_lru = collections.OrderedDict()     # file_idx -> None
        try:
            for fi, start, count in coords:
                payloads = readers[fi].read_range(start, count)
                open_lru[fi] = None
                open_lru.move_to_end(fi)
                if len(open_lru) > self._MAX_OPEN_READERS:
                    oldest, _ = open_lru.popitem(last=False)
                    readers[oldest].release()
                for payload in payloads:
                    ex = tfrecord.decode_example(payload)
                    yield parse(ex) if parse else ex
        finally:
            # handles reopen on demand, so release everything at epoch end
            # (incl. GeneratorExit) — a finite pass must not pin fds
            for r in readers:
                r.release()

    def _file_source(self):
        files = self._my_files()
        if not files:
            raise ValueError("dataset matched no input files")
        cycle, block = getattr(self, "_interleave", (1, 1))

        def gen():
            if cycle <= 1 or len(files) <= 1:
                for path in files:
                    yield from self._reader(path)
                return
            # deterministic round-robin interleave (tf.data's default
            # ordering): `cycle` files open at once, `block` records
            # pulled from each in turn; an exhausted slot refills with
            # the next file
            pending = iter(files)
            slots = []
            for path in pending:
                slots.append(self._reader(path))
                if len(slots) == cycle:
                    break
            while slots:
                for k in range(len(slots)):
                    if slots[k] is None:
                        continue
                    for _ in range(block):
                        try:
                            yield next(slots[k])
                        except StopIteration:
                            nxt = next(pending, None)
                            slots[k] = (self._reader(nxt)
                                        if nxt is not None else None)
                            break
                slots = [s for s in slots if s is not None]
        return gen()

    @property
    def file_rooted(self):
        """True when this dataset reads straight from a file list (so
        `interleave()` applies and `shard()` is file-granular).  Indexed
        roots are excluded: they address records directly, so interleave
        and file-granular sharding don't apply."""
        return (getattr(self, "_files", None) is not None
                and getattr(self, "_indexed", None) is None
                and getattr(self, "_columnar", None) is None
                and self._parent is None)

    def interleave(self, cycle_length=4, block_length=1):
        """Mix records round-robin from `cycle_length` concurrently-open
        files, `block_length` records at a time (the ordering of
        tf.data's deterministic ``interleave``; reference analog: the
        mnist_tf_ds shard readers).  Only valid directly on a file root
        (call BEFORE map/shuffle).  The point is shuffle quality: with
        file-sequential reading a reservoir shuffle only ever mixes
        records ~buffer_size apart, while interleave spreads each file
        across the whole epoch.  IO/decode parallelism rides
        ``map(fn, num_parallel=N)``, which composes downstream.
        """
        if not self.file_rooted:
            raise ValueError("interleave() applies to a file-rooted "
                             "dataset (from_files/from_tfrecords), before "
                             "other transforms")
        if cycle_length < 1 or block_length < 1:
            raise ValueError("cycle_length and block_length must be >= 1")
        new = Dataset(None)
        new._files = self._files
        new._reader = self._reader
        new._shard_spec = self._shard_spec
        new._interleave = (int(cycle_length), int(block_length))
        new._source = new._file_source
        return new

    def _my_files(self):
        files = self._files
        if self._shard_spec:
            n, i = self._shard_spec
            files = files[i::n]
        return files

    # ------------------------------------------------------------ transforms

    def _chain(self, op):
        return Dataset(None, parent=self, op=op)

    def shard(self, num_shards, index):
        """Keep 1/num_shards of the data for this process.

        File-granular when called directly on a file root (shard FIRST,
        before map/shuffle — then each process only ever opens its own
        shard files) with at least `num_shards` files; record-granular
        (round-robin) otherwise.  The multi-host analog of
        ``ds.shard(num_workers, worker_index)`` (mnist_tf_ds.py:41).
        """
        if not 0 <= index < num_shards:
            raise ValueError(f"shard index {index} not in [0, {num_shards})")
        if (self._parent is None
                and getattr(self, "_columnar", None) is not None
                and self._shard_spec is None):
            # columnar root: file-granular slice (each worker decodes only
            # its own shard files).  Fail FAST when files can't cover the
            # shards — an empty worker would otherwise crash mid-training
            # (and deadlock SPMD collectives); write >= num_shards files
            # or use from_indexed_tfrecords for record-granular sharding.
            if len(self._files) < num_shards:
                raise ValueError(
                    f"shard({num_shards}): only {len(self._files)} shard "
                    "files — the columnar root shards at file granularity; "
                    "write more shard files or use from_indexed_tfrecords")
            return Dataset._columnar_root(self._files, dict(self._columnar),
                                          (num_shards, index))
        if (self._parent is None
                and getattr(self, "_indexed", None) is not None
                and self._shard_spec is None):
            # indexed root: record/block-granular slice of the (permuted)
            # coordinate list — balanced shards regardless of file layout,
            # and this worker reads only its own records
            return Dataset._indexed_root(self._files, dict(self._indexed),
                                         (num_shards, index))
        if (self._parent is None
                and getattr(self, "_files", None) is not None
                and getattr(self, "_indexed", None) is None
                and self._shard_spec is None
                and len(self._files) >= num_shards):
            new = Dataset(None)
            new._files = self._files
            new._reader = self._reader
            new._shard_spec = (num_shards, index)
            if getattr(self, "_interleave", None):
                new._interleave = self._interleave
            new._source = new._file_source
            return new
        return self._chain(
            lambda it: (r for j, r in enumerate(it) if j % num_shards == index))

    def map(self, fn, num_parallel=None):
        """Apply `fn` to every record.

        ``num_parallel=N`` runs `fn` on a bounded thread pool (2N records
        in flight, output order preserved) — the tf.data
        ``num_parallel_calls`` analog.  Worth it when `fn` releases the
        GIL (PIL JPEG decode, numpy resize: the image pipeline); pure-
        Python fns gain nothing.
        """
        if not num_parallel or num_parallel <= 1:
            return self._chain(lambda it: (fn(r) for r in it))

        def op(it, _n=int(num_parallel)):
            import concurrent.futures as cf
            from collections import deque
            with cf.ThreadPoolExecutor(_n) as pool:
                window = deque()
                try:
                    for r in it:
                        window.append(pool.submit(fn, r))
                        if len(window) >= 2 * _n:
                            yield window.popleft().result()
                    while window:
                        yield window.popleft().result()
                finally:
                    for f in window:   # consumer stopped early / fn raised
                        f.cancel()
        return self._chain(op)

    def filter(self, pred):
        """Keep records where `pred(record)` is true."""
        return self._chain(lambda it: (r for r in it if pred(r)))

    def cache(self):
        """Materialize upstream records in memory during the first FULL
        pass; later iterations — including `repeat` epochs — replay from
        memory instead of re-reading/re-parsing files (tf.data
        ``.cache()``).  Place before `shuffle` so per-epoch reshuffling
        still applies.  A partial iteration (early break) does not mark
        the cache complete.

        Replay yields the SAME objects each pass (no defensive copy —
        the same trade tf.data makes): a downstream `map` fn that
        mutates records in place (e.g. ``arr -= mean`` on a cached
        numpy array) would corrupt the cache cumulatively across
        epochs.  Map fns over cached data must return new values —
        the bundled image transforms already do."""
        state = {"filled": False, "records": None}

        def op(it):
            if state["filled"]:
                yield from state["records"]
                return
            buf = []
            for r in it:
                buf.append(r)
                yield r
            state["records"], state["filled"] = buf, True
        return self._chain(op)

    def skip(self, n):
        """Skip the first `n` records — the resume-from-position primitive:
        the pipeline is deterministic for a fixed seed, so a restart that
        knows how many records it consumed (steps x batch_size) skips to
        exactly where training stopped instead of re-seeing data
        (mid-epoch resume; the reference's TF-callback checkpoints could
        only resume on epoch boundaries).

        Placement matters with `repeat()`: upstream of repeat the skip
        re-applies EVERY epoch; for resume, call it on the repeated
        stream — ``ds.repeat(E).skip(total_consumed)`` — so it skips the
        total once.
        """
        if n < 0:
            raise ValueError("skip count must be >= 0")
        import itertools
        return self._chain(lambda it: itertools.islice(it, n, None))

    def shuffle(self, buffer_size, seed=0):
        """Windowed shuffle with an O(buffer_size) reservoir, like
        ``tf.data.Dataset.shuffle``: deterministic for a fixed seed, and
        `repeat()` reseeds per epoch (seed + epoch index)."""
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1")

        def op(it, _epoch=0, _seed=seed, _n=buffer_size):
            rng = random.Random(_seed * 1_000_003 + _epoch)
            buf = []
            for r in it:
                buf.append(r)
                if len(buf) >= _n:
                    j = rng.randrange(len(buf))
                    buf[j], buf[-1] = buf[-1], buf[j]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf
        return self._chain(op)

    def repeat(self, epochs=None):
        """Iterate the upstream pipeline `epochs` times (None = forever).
        Each epoch rebuilds the chain with the epoch index threaded into
        shuffle ops, so shuffle order differs per epoch but is reproducible."""

        ds = Dataset(None, parent=self, op=None)
        ds._repeat_epochs = epochs
        return ds

    def batch(self, batch_size, drop_remainder=True, pad_tail=False):
        """Stack consecutive records into columnar numpy batches.

        Tuples become tuples of arrays, dicts become dicts of arrays,
        scalars one array (the `DataFeed.next_numpy_batch` conventions).
        `drop_remainder=True` (default) keeps every batch the same shape —
        no jit recompiles; `pad_tail=True` instead repeat-pads the final
        short batch up to `batch_size`.
        """
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")

        def op(it):
            from .feed import pad_batch
            buf = []
            for r in it:
                buf.append(r)
                if len(buf) == batch_size:
                    yield _stack(buf)
                    buf = []
            if buf and not drop_remainder:
                b = _stack(buf)
                yield pad_batch(b, batch_size) if pad_tail else b
            elif buf and pad_tail:
                yield pad_batch(_stack(buf), batch_size)
        return self._chain(op)

    # ------------------------------------------------------------- terminals

    def __iter__(self):
        return self._build(epoch=0)

    def _build(self, epoch):
        if getattr(self, "_repeat_epochs", _MISSING) is not _MISSING:
            return self._iter_repeated()
        if self._parent is None:
            # epoch-aware roots (indexed global shuffle) get the epoch index
            # like shuffle ops do, so repeat() re-permutes per epoch
            src = getattr(self, "_epoch_source", None)
            return iter(src(epoch)) if src is not None else iter(self._source())
        upstream = self._parent._build(epoch)
        return iter(self._apply_op(upstream, epoch))

    def _apply_op(self, upstream, epoch):
        try:
            return self._op(upstream, _epoch=epoch)
        except TypeError:
            return self._op(upstream)

    def _iter_repeated(self):
        epochs = self._repeat_epochs
        epoch = 0
        while epochs is None or epoch < epochs:
            yield from self._parent._build(epoch)
            epoch += 1

    def prefetch(self, buffer_size=2):
        """Host-side pipeline stage: upstream records are produced by a
        background daemon thread into a bounded queue, so file reads,
        parsing, and batching overlap the consumer's compute (the
        tf.data ``prefetch`` analog, for the host half; pair with
        `prefetch_to_device` for the HBM half).  Upstream exceptions
        re-raise in the consumer."""
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1")

        def op(it):
            import queue as queue_mod
            import threading

            q = queue_mod.Queue(maxsize=buffer_size)
            stop = threading.Event()
            END, ERR = object(), object()

            def _put(item):
                # bounded put that gives up when the consumer is gone, so
                # an abandoned iteration never pins this thread (plus the
                # upstream iterator's open files/buffers) forever
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        return True
                    except queue_mod.Full:
                        continue
                return False

            def producer():
                try:
                    for item in it:
                        if not _put(item):
                            return
                    _put(END)
                except BaseException as e:   # surface in the consumer
                    # single bounded put: the marker and payload travel
                    # together so an abandoned consumer can't strand this
                    # thread between the two enqueues
                    _put((ERR, e))

            t = threading.Thread(target=producer, daemon=True,
                                 name="dataset-prefetch")
            t.start()
            try:
                while True:
                    item = q.get()
                    if item is END:
                        return
                    if type(item) is tuple and len(item) == 2 \
                            and item[0] is ERR:
                        raise item[1]
                    yield item
            finally:
                # consumer done, broken out, or GC'd: release the producer
                stop.set()
        return self._chain(op)

    def prefetch_to_device(self, sharding=None, depth=2):
        """Terminal stage: device-resident batches with `depth` host->HBM
        transfers in flight (see `feed.device_prefetch`)."""
        from .feed import device_prefetch
        return device_prefetch(iter(self), sharding=sharding, depth=depth)

    def take(self, n):
        """First `n` records (a terminal convenience for tests/debugging)."""
        out = []
        if n <= 0:
            return out
        for r in self:
            out.append(r)
            if len(out) >= n:
                break
        return out


_MISSING = object()


def _expand_paths(paths):
    from .tfrecord import INDEX_SUFFIX
    if isinstance(paths, str):
        import os
        if os.path.isdir(paths):
            out = sorted(
                p for f in os.listdir(paths)
                if not f.startswith(("_", "."))
                and not f.endswith(INDEX_SUFFIX)   # sidecar indexes
                and os.path.isfile(p := os.path.join(paths, f)))
        else:
            out = sorted(p for p in glob_mod.glob(paths)
                         if not p.endswith(INDEX_SUFFIX))
        return out
    return sorted(str(p) for p in paths)


def _stack(records):
    """Columnar stack following the DataFeed conventions."""
    import numpy as np

    first = records[0]
    if isinstance(first, dict):
        return {k: np.asarray([r[k] for r in records]) for k in first}
    if isinstance(first, (tuple, list)):
        return tuple(np.asarray([r[i] for r in records])
                     for i in range(len(first)))
    return np.asarray(records)
