"""Shared param-tree path utilities (used by `lora` and `quantize`).

Paths are slash-joined key sequences ("layer_0/attn/query/kernel"), the
addressing scheme both modules expose to users for selecting kernels by
regex.
"""


def flatten_with_paths(params):
    """-> ({path: leaf} in canonical flatten order, treedef)."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = {}
    for path, leaf in flat:
        key = "/".join(getattr(p, "key", str(getattr(p, "idx", p)))
                       for p in path)
        out[key] = leaf
    return out, treedef
