"""Ring attention: exact attention over sequence shards with O(S/N) memory
per device and K/V blocks rotated around the mesh axis via `lax.ppermute`.

Long-context machinery is absent from the reference (SURVEY.md §5
"Long-context / sequence parallelism: absent"); here it is first-class: the
sequence axis of q/k/v is sharded over a mesh axis (context parallelism) and
each device computes its queries against every K/V block as the blocks flow
around the ring, maintaining a numerically-stable online softmax
(flash-attention style running max/denominator), so the result is EXACTLY
dense attention.

Collectives ride ICI: each step's ppermute is a neighbor exchange, which is
the optimal pattern on a TPU torus.

Two local-compute paths:
- `use_flash=True` (default on TPU): each ring step runs the pallas flash
  kernel on (q_local, k_blk, v_blk) and merges the per-block outputs by
  their logsumexp — ring handles the cross-device axis, the kernel the
  on-device blocks, and the [S/N, S/N] score tile never hits HBM.  Causal
  steps pick the right kernel mode per device via `lax.switch` (past block
  → non-causal, diagonal → causal, future → skipped with zero weight).
- `use_flash=False`: a pure-jnp online-softmax update (the CPU test mesh
  path, and the reference semantics the kernel path is tested against).
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax


def _online_update(o, m, l, logits, v_blk):
    """One block's contribution via streaming softmax.

    o: [B, Sq, H, D] accumulated (unnormalized) output
    m: [B, H, Sq]    running max
    l: [B, H, Sq]    running denominator
    logits: [B, H, Sq, Sk] this block's scores (f32, already masked)
    """
    m_blk = jnp.max(logits, axis=-1)                       # [B,H,Sq]
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows: exp(-inf - -inf) -> use safe max
    alpha = jnp.exp(m - m_new)                              # rescale old
    p = jnp.exp(logits - m_new[..., None])                  # [B,H,Sq,Sk]
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk).astype(jnp.float32)
    return o_new, m_new, l_new


def _kv_repeat(q, k_blk, v_blk):
    """Broadcast narrow (GQA) k/v heads to the query head count — on-device,
    after the collectives moved only the narrow tensors."""
    H, H_kv = q.shape[2], k_blk.shape[2]
    if H == H_kv:
        return k_blk, v_blk
    if H % H_kv:
        raise ValueError(
            f"q heads {H} must be divisible by kv heads {H_kv}")
    rep = H // H_kv
    return jnp.repeat(k_blk, rep, axis=2), jnp.repeat(v_blk, rep, axis=2)


def _ring_jnp_local(q, k, v, axis_name, causal):
    """Body running under shard_map: q/k/v are the LOCAL sequence blocks.

    k/v may carry fewer (GQA) heads than q — they ride the ring narrow and
    are broadcast per step."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))

    q32 = q
    o = jnp.zeros((B, Sq, H, D), jnp.float32)
    m = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Sq), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step_fn(carry, step):
        o, m, l, k_blk, v_blk = carry
        # which global block is currently resident: blocks rotate forward,
        # so at `step` we hold block (my_idx - step) mod N
        blk_idx = (my_idx - step) % axis_size
        k_use, v_use = _kv_repeat(q, k_blk, v_blk)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32, k_use).astype(jnp.float32)
        logits = logits * scale
        if causal:
            Sk = k_blk.shape[1]
            q_pos = my_idx * Sq + jnp.arange(Sq)            # global q positions
            k_pos = blk_idx * Sk + jnp.arange(Sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, -1e30)
        o, m, l = _online_update(o, m, l, logits, v_use)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_next, v_next), None

    (o, m, l, _, _), _ = lax.scan(step_fn, (o, m, l, k, v),
                                  jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_flash_local(q, k, v, axis_name, causal, interpret):
    """Ring body whose per-step local compute is the pallas flash kernel.

    Per step the kernel returns (out_blk normalized within the block,
    lse_blk); blocks merge by logsumexp weights — algebraically identical
    to the online update, so the result stays exact.
    """
    from tensorflowonspark_tpu.ops.flash_attention import (
        flash_attention_with_lse)
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Sq, H, D = q.shape

    attn = functools.partial(flash_attention_with_lse, interpret=interpret)
    o = jnp.zeros((B, Sq, H, D), jnp.float32)   # lse-weighted accumulator
    m = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)  # running max lse
    l = jnp.zeros((B, H, Sq), jnp.float32)      # running total weight

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step_fn(carry, step):
        o, m, l, k_blk, v_blk = carry
        blk_idx = (my_idx - step) % axis_size
        # GQA kv stays NARROW all the way into the kernel (round 5: the
        # flash kernel indexes kv blocks per q-head group itself) — the
        # repeated kv no longer materializes even locally
        k_use, v_use = k_blk, v_blk

        if causal:
            # 0: past block (fully visible), 1: diagonal (causal within),
            # 2: future block (fully masked — contribute zero weight)
            case = jnp.where(blk_idx < my_idx, 0,
                             jnp.where(blk_idx == my_idx, 1, 2))
            out_blk, lse_blk = lax.switch(
                case,
                [lambda q, k, v: attn(q, k, v, causal=False),
                 lambda q, k, v: attn(q, k, v, causal=True),
                 lambda q, k, v: (jnp.zeros_like(q),
                                  jnp.full((B, H, Sq), -jnp.inf,
                                           jnp.float32))],
                q, k_use, v_use)
        else:
            out_blk, lse_blk = attn(q, k_use, v_use, causal=False)

        # merge by lse: out_blk carries weight exp(lse_blk)
        m_new = jnp.maximum(m, lse_blk)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        w = jnp.where(jnp.isfinite(lse_blk), jnp.exp(lse_blk - m_safe), 0.0)
        o = (o * alpha.transpose(0, 2, 1)[..., None]
             + out_blk.astype(jnp.float32)
             * w.transpose(0, 2, 1)[..., None])
        l = l * alpha + w
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (o, m_new, l, k_next, v_next), None

    (o, m, l, _, _), _ = lax.scan(step_fn, (o, m, l, k, v),
                                  jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_attention_local(q, k, v, axis_name, causal, use_flash=None,
                          interpret=None):
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        return _ring_flash_local(q, k, v, axis_name, causal, interpret)
    return _ring_jnp_local(q, k, v, axis_name, causal)


def ring_attention(q, k, v, axis_name="tp", causal=True, mesh=None,
                   use_flash=None, interpret=None, batch_axes=None):
    """Exact attention with q/k/v sequence-sharded over `axis_name`.

    Call either (a) inside an existing shard_map/jit context where
    `axis_name` is bound — then this runs the local body directly — or
    (b) at top level with `mesh` provided (concrete, or abstract under
    jit), in which case it wraps itself in shard_map with the sequence dim
    of [B, S, H, D] sharded over the axis and the batch dim over
    `batch_axes` (None = replicated).
    """
    if mesh is None:
        return _ring_attention_local(q, k, v, axis_name, causal,
                                     use_flash=use_flash,
                                     interpret=interpret)

    from jax.sharding import PartitionSpec as P
    shard_map = _get_shard_map()
    spec = P(batch_axes, axis_name, None, None)
    fn = functools.partial(_ring_attention_local, axis_name=axis_name,
                           causal=causal, use_flash=use_flash,
                           interpret=interpret)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def _get_shard_map():
    """shard_map normalized to the current kwarg spelling (compat.py owns
    the version translation — check_vma vs the older check_rep)."""
    from tensorflowonspark_tpu.compat import shard_map
    return shard_map()
