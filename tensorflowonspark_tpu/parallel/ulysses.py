"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

The second long-context strategy next to ring attention (the reference has
neither — SURVEY.md §5 "Long-context / sequence parallelism: absent").
Activations travel the network sequence-sharded [B, S/N, H, D]; around the
attention core two `lax.all_to_all` collectives swap the sharded axis so
attention sees full sequences with H/N local heads:

    [B, S/N, H, D] --all2all--> [B, S, H/N, D] --attn--> --all2all--> back

Each all-to-all moves only 1/N of the activation bytes per device and rides
ICI; the attention core itself is the unsharded on-device kernel, so this
composes directly with the pallas flash kernel (ops/flash_attention) — in
contrast to ring attention, which pays N neighbor exchanges of K/V but
never materializes the full sequence on any device.  Rule of thumb: Ulysses
when heads >= N and HBM fits S (cheaper collectives, full-power kernel);
ring when S alone exceeds HBM.
"""
import functools

import jax.numpy as jnp
from jax import lax


def _ulysses_local(q, k, v, axis_name, causal, attn_fn, narrow_ok=False):
    """Body under shard_map: q/k/v are [B, S/N, H, D] local blocks.
    ``narrow_ok``: the attention core accepts GQA-narrow kv directly
    (the default flash/reference cores do since round 5), so the local
    post-all-to-all repeat is skipped; custom ``attn_fn``s keep it."""
    axis_size = lax.psum(1, axis_name)

    def seq_to_heads(x):
        # [B, S/N, H, D] -> [B, S, H/N, D]: split heads over the axis,
        # concatenate the gathered sequence blocks
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    H_q, H_kv = q.shape[2], k.shape[2]
    if H_q % axis_size:
        raise ValueError(
            f"n_heads={H_q} must be divisible by the ulysses axis "
            f"size {axis_size}")
    if H_q != H_kv:
        # GQA: exchange kv as narrow as the axis allows — pre-repeat only
        # until the axis divides the head count (bytes moved scale with
        # pre/rep), broadcast the rest locally after the all-to-all.  The
        # jnp.repeat ordering keeps kv group g aligned with the q heads
        # that land on the same device.
        if H_q % H_kv:
            raise ValueError(
                f"n_heads={H_q} must be divisible by n_kv_heads={H_kv}")
        rep = H_q // H_kv
        pre = next(p for p in range(1, rep + 1)
                   if rep % p == 0 and (H_kv * p) % axis_size == 0)
        if pre > 1:
            k = jnp.repeat(k, pre, axis=2)
            v = jnp.repeat(v, pre, axis=2)
    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if not narrow_ok:
        # a custom attention core may not understand GQA-narrow kv;
        # the contiguous head split keeps group g's kv on the same
        # device as its q heads, so the local repeat mapping is exact
        from tensorflowonspark_tpu.parallel.ring_attention import _kv_repeat
        kg, vg = _kv_repeat(qg, kg, vg)
    out = attn_fn(qg, kg, vg, causal)
    return heads_to_seq(out)


def _default_attn(q, k, v, causal):
    """Full-sequence attention core: pallas flash on TPU, dense elsewhere."""
    from tensorflowonspark_tpu.ops import default_interpret
    from tensorflowonspark_tpu.ops.flash_attention import (
        attention_reference, flash_attention)
    if default_interpret():
        return attention_reference(q, k, v, causal=causal)
    return flash_attention(q, k, v, causal=causal)


def ulysses_attention(q, k, v, axis_name="tp", causal=True, mesh=None,
                      attn_fn=None, batch_axes=None):
    """Exact attention with q/k/v sequence-sharded over `axis_name`.

    Same calling contract as ring_attention: either inside an existing
    shard_map/jit context where `axis_name` is bound, or at top level with
    `mesh` given (concrete or abstract under jit) — then it wraps itself in
    shard_map with the sequence dim of [B, S, H, D] sharded over the axis
    and the batch dim over `batch_axes` (None = replicated).
    """
    narrow_ok = attn_fn is None      # the default cores take GQA-narrow kv
    attn_fn = attn_fn or _default_attn
    if mesh is None:
        return _ulysses_local(q, k, v, axis_name, causal, attn_fn,
                              narrow_ok=narrow_ok)

    from jax.sharding import PartitionSpec as P
    from tensorflowonspark_tpu.parallel.ring_attention import _get_shard_map
    shard_map = _get_shard_map()
    spec = P(batch_axes, axis_name, None, None)
    fn = functools.partial(_ulysses_local, axis_name=axis_name,
                           causal=causal, attn_fn=attn_fn,
                           narrow_ok=narrow_ok)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
