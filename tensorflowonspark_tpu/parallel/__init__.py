"""TPU-native parallelism: mesh construction, sharding rules, train-step
harness, and long-context primitives.

This package is what replaces the reference's delegation to TensorFlow
distribution strategies (SURVEY.md §2.3): where TFoS assembled TF_CONFIG and
let `MultiWorkerMirroredStrategy` allreduce over gRPC, this framework owns
the parallelism — a `jax.sharding.Mesh` over dp/fsdp/pp/tp axes, pjit-sharded
train steps with gradient allreduce over ICI, Megatron-style tensor/sequence
parallel layers, ring and Ulysses (all-to-all) attention for context
parallelism, expert parallelism for MoE, and pipeline parallelism via
collective permutes.
"""
from .mesh import (MeshSpec, build_hybrid_mesh, build_mesh,  # noqa: F401
                   detect_num_slices, local_mesh_spec)
