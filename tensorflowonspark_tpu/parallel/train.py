"""pjit train-step harness: the compute engine the examples plug into.

Replaces the reference's delegation to `tf.distribute` strategies inside the
user map_fun (SURVEY.md §2.3): here the framework owns the step — a jitted
function with explicit in/out shardings over the cluster mesh, so XLA
inserts gradient allreduce over ICI from the sharding layout alone (no
NCCL/gRPC plumbing).  Supports gradient accumulation (lax.scan over
microbatches), bfloat16 compute with float32 params, and rematerialization.
"""
import logging
from typing import Any, NamedTuple

from . import mesh as mesh_mod
from . import sharding as sharding_mod

logger = logging.getLogger(__name__)


class TrainState(NamedTuple):
    step: Any
    params: Any
    opt_state: Any


def create_train_state(params, optimizer, mesh=None, param_shardings=None):
    """Initialize TrainState, placing params/opt state on the mesh."""
    import jax
    import jax.numpy as jnp

    if mesh is not None:
        if param_shardings is None:
            param_shardings = sharding_mod.infer_param_shardings(params, mesh)
        params = sharding_mod.shard_params(params, param_shardings)
    opt_state = optimizer.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state)


def make_train_step(loss_fn, optimizer, mesh=None, param_shardings=None,
                    grad_accum=1, compute_dtype=None, donate=True,
                    example_params=None, layouts=None):
    """Build the jitted train step.

    `loss_fn(params, batch, rng) -> scalar loss` — the mean over the LOCAL
    shard; with the batch sharded over dp/fsdp and params replicated (or
    sharded), jit's sharding propagation makes XLA emit the gradient
    allreduce automatically.

    An optimizer exposing a single-pass ``apply(grads, state, params) ->
    (params, state)`` (ops/fused_optim's adamw_fused/lion_fused, via
    ``optim.make_optimizer``) takes that path instead of
    ``update`` + ``optax.apply_updates``: the parameter write happens
    inside the fused kernel's one pass over the state, and jit donation
    recycles the old param/moment buffers.

    ``example_params`` (arrays or ShapeDtypeStructs matching the real
    parameters) is only needed with `param_shardings` AND an optimizer
    whose state the shardings alone cannot place — optim8bit's quantized
    moments, which then shard along their block axis instead of
    replicating (see _quantized_shardings).

    ``layouts`` — the SAME pytree the 8-bit optimizer was built with
    (``optim8bit.layouts_for_shardings(params, shardings)``); declares
    that each param's quantized state uses the shard-aligned block
    layout, so it shards by the param's FULL spec (fsdp and tp axes).
    Explicit on purpose: the aligned payload's shape coincides with the
    row-major one in the common case, so it cannot be detected.

    Returns `train_step(state, batch, rng) -> (state, metrics)`.
    """
    import jax
    import jax.numpy as jnp

    def _loss(params, batch, rng):
        if compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype)
                if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating)
                else x, params)
        return loss_fn(params, batch, rng)

    def _step(state, batch, rng):
        if grad_accum > 1:
            micro = jax.tree_util.tree_map(
                lambda x: x.reshape((grad_accum, x.shape[0] // grad_accum)
                                    + x.shape[1:]), batch)

            def body(carry, mb):
                loss, g = jax.value_and_grad(_loss)(state.params, mb, rng)
                acc_loss, acc_g = carry
                return (acc_loss + loss,
                        jax.tree_util.tree_map(jnp.add, acc_g, g)), None

            zeros = jax.tree_util.tree_map(jnp.zeros_like, state.params)
            (loss, grads), _ = jax.lax.scan(body, (0.0, zeros), micro)
            loss = loss / grad_accum
            grads = jax.tree_util.tree_map(lambda g: g / grad_accum, grads)
        else:
            loss, grads = jax.value_and_grad(_loss)(state.params, batch, rng)

        import optax
        fused_apply = getattr(optimizer, "apply", None)
        if callable(fused_apply):
            # single-pass fused optimizer: param write fused into the
            # kernel's one pass over grad/moments (no apply_updates pass)
            params, opt_state = fused_apply(grads, state.opt_state,
                                            state.params)
        else:
            updates, opt_state = optimizer.update(grads, state.opt_state,
                                                  state.params)
            params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=params,
                               opt_state=opt_state)
        # the fused path computes this same reduction for its clip scale;
        # XLA CSEs the two, so the metric stays free there
        metrics = {"loss": loss,
                   "grad_norm": optax.global_norm(grads)}
        return new_state, metrics

    if mesh is None and param_shardings is not None:
        # derive the mesh from the shardings rather than silently
        # compiling an unsharded step
        leaves = jax.tree_util.tree_leaves(param_shardings)
        mesh = next((s.mesh for s in leaves if hasattr(s, "mesh")), None)
    if mesh is None:
        return jax.jit(_step, donate_argnums=(0,) if donate else ())

    from jax.sharding import NamedSharding, PartitionSpec
    repl = NamedSharding(mesh, PartitionSpec())
    batch_shard = mesh_mod.batch_sharding(mesh)
    if param_shardings is None:
        if callable(getattr(optimizer, "apply", None)):
            # fused-optimizer path: pallas_call is a custom call GSPMD
            # cannot partition, so sharding does not propagate through it
            # the way it does through the optax update — left unpinned,
            # the compiler picks fresh output shardings and the donated
            # state aliases fail at runtime on mismatched shard sizes.
            # Pin the state outputs to the incoming placement, derived
            # from the first state actually passed in.
            cache = {}

            def step(state, batch, rng):
                if "fn" not in cache:
                    state_sh = jax.tree_util.tree_map(
                        lambda x: x.sharding
                        if isinstance(x.sharding, NamedSharding) else repl,
                        state)
                    cache["fn"] = jax.jit(
                        _step,
                        in_shardings=(state_sh, batch_shard, repl),
                        out_shardings=(state_sh, repl),
                        donate_argnums=(0,) if donate else ())
                return cache["fn"](state, batch, rng)
            return step
        state_shardings = None  # let jit infer from input placement
        in_shardings = (None, batch_shard, repl)
        out_shardings = (None, repl)
    else:
        state_shardings = TrainState(
            step=repl, params=param_shardings,
            opt_state=_opt_state_shardings(optimizer, param_shardings, repl,
                                           example_params, layouts))
        in_shardings = (state_shardings, batch_shard, repl)
        out_shardings = (state_shardings, repl)

    return jax.jit(_step, in_shardings=in_shardings,
                   out_shardings=out_shardings,
                   donate_argnums=(0,) if donate else ())


def _opt_state_shardings(optimizer, param_shardings, repl,
                         example_params=None, layouts=None):
    """Mirror param shardings onto optimizer slots (mu/nu mirror the param
    tree and inherit its shardings; scalar slots like counts replicate).

    The fused single-pass optimizers (ops/fused_optim's FusedAdamWState /
    FusedLionState) are placed by the NamedTuple recursion below: their
    moments keep each parameter's exact shape and mirror the param
    pytree, so every moment shards by its param's OWN spec — fsdp and tp
    axes alike, the placement f32 optax moments get — and the kernel's
    (rows, 128) blocking happens per shard inside the jitted step with
    no cross-shard blocks (the fused analog of optim8bit's shard-aligned
    layouts, with alignment by construction instead of a layouts= knob).

    ``example_params`` (a pytree of arrays or ShapeDtypeStructs matching
    the real parameters) enables shape-aware placement for state the
    shardings alone cannot describe — today that is optim8bit's
    blockwise-quantized moments, which shard along their flat block axis
    when the divisibility works out (see _quantized_shardings)."""
    import jax
    import jax.numpy as jnp

    if example_params is not None:
        shapes = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            example_params)
        state_shapes = jax.eval_shape(optimizer.init, shapes)
        return _map_state(state_shapes, param_shardings, repl,
                          param_shapes=shapes, layouts=layouts)
    dummy = jax.tree_util.tree_map(lambda s: jnp.zeros(()), param_shardings)
    try:
        state = optimizer.init(dummy)
    except ValueError as e:
        # e.g. adamw8bit built with layouts=: its init is shape-dependent
        # and cannot run on placeholder scalars
        raise ValueError(
            "deriving optimizer-state shardings from placeholder scalar "
            "params failed — an optimizer with shape-dependent state "
            "(e.g. adamw8bit with layouts=) needs example_params passed "
            "to make_train_step") from e
    return _map_state(state, param_shardings, repl)


def _map_state(state, param_shardings, repl, param_shapes=None,
               layouts=None):
    import jax

    params_struct = jax.tree_util.tree_structure(param_shardings)
    if jax.tree_util.tree_structure(state) == params_struct:
        return param_shardings
    if _is_params_shaped_quantized(state, params_struct):
        # a quantized-moments tree mirroring the params (ANY container
        # type — dict, NamedTuple, list); checked BEFORE the NamedTuple
        # recursion because Quantized is itself a NamedTuple and naive
        # descent would walk into its q/scale fields and lose the
        # params pairing
        if param_shapes is not None:
            return _quantized_shardings(state, param_shardings, repl,
                                        param_shapes, layouts)
        logger.warning(
            "8-bit optimizer state is replicated under explicit param "
            "shardings; pass example_params to make_train_step to shard "
            "it along the block axis")
        return jax.tree_util.tree_map(lambda _: repl, state)
    if hasattr(state, "_fields"):  # NamedTuple (ScaleByAdamState etc.)
        return type(state)(*(_map_state(getattr(state, f), param_shardings,
                                        repl, param_shapes, layouts)
                             for f in state._fields))
    if isinstance(state, (tuple, list)):
        return type(state)(_map_state(s, param_shardings, repl, param_shapes,
                                      layouts)
                           for s in state)
    if _has_quantized(state):
        if param_shapes is not None:
            # shape-aware path (make_train_step(..., example_params=...)):
            # each param's quantized moments shard along their flat block
            # axis when each mesh shard owns a whole number of blocks
            return _quantized_shardings(state, param_shardings, repl,
                                        param_shapes, layouts)
        # optim8bit state without shape info (checked AFTER container
        # recursion so only the subtrees that actually hold Quantized
        # replicate — a chained f32 ema/accumulator state still gets
        # param shardings): blockwise-quantized payloads are flat
        # [n_blocks, block] views, and without the parameter shapes the
        # divisibility cannot be checked, so they are REPLICATED (loudly
        # — full-size int8 state per chip; still 4x smaller than
        # replicated f32, but NOT sharded like f32 moments would be
        # under fsdp).  Pass example_params to make_train_step for the
        # sharded placement.
        logger.warning(
            "8-bit optimizer state is replicated under explicit param "
            "shardings; pass example_params to make_train_step to shard "
            "it along the block axis")
    return jax.tree_util.tree_map(lambda _: repl, state)


def _quantized_shardings(q_state_shapes, param_shardings, repl,
                         param_shapes, layouts=None):
    """Shardings for a params-shaped tree of Quantized shape-structs.

    Preferred route — shard-aligned layout, declared via ``layouts``
    (the same tree the optimizer was built with): each param's blocks
    were computed over its logical shards (shard-major flatten), so
    q/scale shard on dim 0 by the param's FULL spec (fsdp AND tp axes)
    with zero extra communication.  The layout is NEVER guessed from
    shapes: an aligned payload's shape coincides with the row-major one
    whenever each shard's elements are a block multiple (the common
    production case), and sharding a row-major payload by a multi-dim
    spec would make GSPMD reshard the int8 state every step.  A layout
    that doesn't match the declared sharding or the payload shape is an
    error, not a silent fallback.

    Fallback — dim-0-only: a layout-less payload under fsdp-style row
    sharding still shards on its block axis when each shard owns a whole
    number of blocks (row-major flatten IS shard-major there).  Anything
    else (a TP axis in the spec without a declared layout, indivisible
    blocks) replicates that param's state, loudly.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from tensorflowonspark_tpu.optim8bit import (
        Quantized, expected_blocks, shard_layout)

    def per_param(sharding, qt, pshape, layout):
        spec = tuple(getattr(sharding, "spec", ()) or ())
        mesh = getattr(sharding, "mesh", None)
        n_blocks, block = qt.q.shape
        shape = tuple(pshape.shape)
        if layout is not None and any(n > 1 for n in layout):
            if layout != shard_layout(shape, sharding):
                raise ValueError(
                    f"declared quantized-state layout {layout} does not "
                    f"match sharding {spec} for param shape {shape} "
                    "(build both from optim8bit.layouts_for_shardings "
                    "with the same shardings)")
            if n_blocks != expected_blocks(shape, layout, block):
                raise ValueError(
                    f"quantized payload {tuple(qt.q.shape)} for param "
                    f"shape {shape} was not built with layout {layout} "
                    "(pass the same layouts= to the optimizer and "
                    "make_train_step)")
            axes = []
            for entry in spec:
                names = (() if entry is None else entry
                         if isinstance(entry, tuple) else (entry,))
                axes.extend(a for a in names if mesh.shape.get(a, 1) > 1)
            s = NamedSharding(mesh, PartitionSpec(tuple(axes), None))
            return Quantized(q=s, scale=s)
        if (mesh is not None and spec and spec[0] is not None
                and all(a is None for a in spec[1:])):
            axis = spec[0]
            n_shards = mesh.shape[axis] if not isinstance(axis, tuple) else 0
            if n_shards and n_blocks % n_shards == 0:
                s = NamedSharding(mesh, PartitionSpec(axis, None))
                return Quantized(q=s, scale=s)
        if any(a is not None for a in spec):
            # the documented loud fallback: a sharded param whose
            # quantized state cannot ride the block axis (layout-less
            # TP sharding or indivisible block count) replicates —
            # build the optimizer with optim8bit.layouts_for_shardings
            # and pass layouts= to make_train_step to shard it
            logger.warning(
                "quantized optimizer state for a param sharded %s "
                "(%d blocks) cannot shard along its block axis; "
                "replicating that param's int8 state (build the "
                "optimizer with layouts=optim8bit.layouts_for_shardings "
                "and pass layouts= to make_train_step)", spec, n_blocks)
        return Quantized(q=repl, scale=repl)

    if layouts is None:
        layouts = jax.tree_util.tree_map(lambda _: None, param_shardings)
    return jax.tree_util.tree_map(
        per_param, param_shardings, q_state_shapes, param_shapes, layouts,
        is_leaf=lambda x: isinstance(x, Quantized))


def _has_quantized(state):
    try:
        from tensorflowonspark_tpu.optim8bit import Quantized
    except Exception:
        return False
    import jax
    found = []
    jax.tree_util.tree_map(
        lambda x: found.append(True) if isinstance(x, Quantized) else None,
        state, is_leaf=lambda x: isinstance(x, Quantized))
    return bool(found)


def _is_params_shaped_quantized(state, params_struct):
    """True when `state` mirrors the params tree with a Quantized subtree
    at every leaf position — the shape of optim8bit's mu/nu_sqrt."""
    try:
        from tensorflowonspark_tpu.optim8bit import Quantized
    except Exception:
        return False
    try:
        flat = params_struct.flatten_up_to(state)
    except (ValueError, TypeError):
        return False
    return bool(flat) and all(isinstance(x, Quantized) for x in flat)


def make_eval_step(forward_fn, mesh=None):
    """Jitted forward/eval step with batch sharded over dp."""
    import jax

    if mesh is None:
        return jax.jit(forward_fn)
    from jax.sharding import NamedSharding, PartitionSpec
    return jax.jit(
        forward_fn,
        in_shardings=(NamedSharding(mesh, PartitionSpec()),
                      mesh_mod.batch_sharding(mesh)),
        out_shardings=mesh_mod.batch_sharding(mesh))


def feed_consensus(has_data):
    """Global stop-consensus for synchronous training over an uneven feed.

    Every process calls this once per step with whether ITS feed produced a
    batch; returns True only while every process has data. The first dry
    process flips the whole cluster to stop on the same step, so sharded
    collectives never go ragged. This replaces the reference's heuristic of
    training only 90% of the per-worker steps to dodge uneven RDD partitions
    (reference: examples/mnist/keras/mnist_spark.py:58-64) with an exact
    consensus; the dropped remainder is bounded by the feed imbalance, and
    callers should df.terminate() to drain it.

    Callers MUST pair this with a bounded feed probe
    (``DataFeed.next_batch(bs, timeout=...)``), never a blocking read: a
    worker blocked in q.get() waiting for records that only arrive after its
    peers advance would never reach this collective, deadlocking the cluster
    until feed_timeout.

    Single-process clusters short-circuit (no collective). Cross-process it
    is one tiny allgather over the cluster fabric (Gloo on CPU hosts, ICI/DCN
    on TPU) per step.
    """
    import jax

    if jax.process_count() <= 1:
        return bool(has_data)
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([1 if has_data else 0], np.int32))
    return bool(np.asarray(flags).min())
