"""pjit train-step harness: the compute engine the examples plug into.

Replaces the reference's delegation to `tf.distribute` strategies inside the
user map_fun (SURVEY.md §2.3): here the framework owns the step — a jitted
function with explicit in/out shardings over the cluster mesh, so XLA
inserts gradient allreduce over ICI from the sharding layout alone (no
NCCL/gRPC plumbing).  Supports gradient accumulation (lax.scan over
microbatches), bfloat16 compute with float32 params, and rematerialization.
"""
import logging
from typing import Any, NamedTuple

from . import mesh as mesh_mod
from . import sharding as sharding_mod

logger = logging.getLogger(__name__)


class TrainState(NamedTuple):
    step: Any
    params: Any
    opt_state: Any


def create_train_state(params, optimizer, mesh=None, param_shardings=None):
    """Initialize TrainState, placing params/opt state on the mesh."""
    import jax
    import jax.numpy as jnp

    if mesh is not None:
        if param_shardings is None:
            param_shardings = sharding_mod.infer_param_shardings(params, mesh)
        params = sharding_mod.shard_params(params, param_shardings)
    opt_state = optimizer.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state)


def make_train_step(loss_fn, optimizer, mesh=None, param_shardings=None,
                    grad_accum=1, compute_dtype=None, donate=True):
    """Build the jitted train step.

    `loss_fn(params, batch, rng) -> scalar loss` — the mean over the LOCAL
    shard; with the batch sharded over dp/fsdp and params replicated (or
    sharded), jit's sharding propagation makes XLA emit the gradient
    allreduce automatically.

    Returns `train_step(state, batch, rng) -> (state, metrics)`.
    """
    import jax
    import jax.numpy as jnp

    def _loss(params, batch, rng):
        if compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype)
                if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating)
                else x, params)
        return loss_fn(params, batch, rng)

    def _step(state, batch, rng):
        if grad_accum > 1:
            micro = jax.tree_util.tree_map(
                lambda x: x.reshape((grad_accum, x.shape[0] // grad_accum)
                                    + x.shape[1:]), batch)

            def body(carry, mb):
                loss, g = jax.value_and_grad(_loss)(state.params, mb, rng)
                acc_loss, acc_g = carry
                return (acc_loss + loss,
                        jax.tree_util.tree_map(jnp.add, acc_g, g)), None

            zeros = jax.tree_util.tree_map(jnp.zeros_like, state.params)
            (loss, grads), _ = jax.lax.scan(body, (0.0, zeros), micro)
            loss = loss / grad_accum
            grads = jax.tree_util.tree_map(lambda g: g / grad_accum, grads)
        else:
            loss, grads = jax.value_and_grad(_loss)(state.params, batch, rng)

        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        import optax
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=params,
                               opt_state=opt_state)
        metrics = {"loss": loss,
                   "grad_norm": optax.global_norm(grads)}
        return new_state, metrics

    if mesh is None:
        return jax.jit(_step, donate_argnums=(0,) if donate else ())

    from jax.sharding import NamedSharding, PartitionSpec
    repl = NamedSharding(mesh, PartitionSpec())
    batch_shard = mesh_mod.batch_sharding(mesh)
    if param_shardings is None:
        state_shardings = None  # let jit infer from input placement
        in_shardings = (None, batch_shard, repl)
        out_shardings = (None, repl)
    else:
        state_shardings = TrainState(
            step=repl, params=param_shardings,
            opt_state=_opt_state_shardings(optimizer, param_shardings, repl))
        in_shardings = (state_shardings, batch_shard, repl)
        out_shardings = (state_shardings, repl)

    return jax.jit(_step, in_shardings=in_shardings,
                   out_shardings=out_shardings,
                   donate_argnums=(0,) if donate else ())


def _opt_state_shardings(optimizer, param_shardings, repl):
    """Mirror param shardings onto optimizer slots (mu/nu mirror the param
    tree and inherit its shardings; scalar slots like counts replicate)."""
    import jax
    import jax.numpy as jnp

    dummy = jax.tree_util.tree_map(lambda s: jnp.zeros(()), param_shardings)
    state = optimizer.init(dummy)
    return _map_state(state, param_shardings, repl)


def _map_state(state, param_shardings, repl):
    import jax

    params_struct = jax.tree_util.tree_structure(param_shardings)
    if jax.tree_util.tree_structure(state) == params_struct:
        return param_shardings
    if hasattr(state, "_fields"):  # NamedTuple (ScaleByAdamState etc.)
        return type(state)(*(_map_state(getattr(state, f), param_shardings, repl)
                             for f in state._fields))
    if isinstance(state, (tuple, list)):
        return type(state)(_map_state(s, param_shardings, repl) for s in state)
    if _has_quantized(state):
        # optim8bit state (checked AFTER container recursion so only the
        # subtrees that actually hold Quantized replicate — a chained f32
        # ema/accumulator state still gets param shardings): blockwise-
        # quantized payloads are flat [n_blocks, block] views whose
        # element order does not follow the parameter's sharded axes, so
        # they are REPLICATED (loudly — full-size int8 state per chip;
        # still 4x smaller than replicated f32, but NOT sharded like f32
        # moments would be under fsdp).  Sharding quantized state needs
        # per-shard quantization, which is future work — see optim8bit
        # module doc.
        logger.warning(
            "8-bit optimizer state is replicated under explicit param "
            "shardings (not fsdp-sharded); per-chip optimizer memory is "
            "the full quantized state")
    return jax.tree_util.tree_map(lambda _: repl, state)


def _has_quantized(state):
    try:
        from tensorflowonspark_tpu.optim8bit import Quantized
    except Exception:
        return False
    import jax
    found = []
    jax.tree_util.tree_map(
        lambda x: found.append(True) if isinstance(x, Quantized) else None,
        state, is_leaf=lambda x: isinstance(x, Quantized))
    return bool(found)


def make_eval_step(forward_fn, mesh=None):
    """Jitted forward/eval step with batch sharded over dp."""
    import jax

    if mesh is None:
        return jax.jit(forward_fn)
    from jax.sharding import NamedSharding, PartitionSpec
    return jax.jit(
        forward_fn,
        in_shardings=(NamedSharding(mesh, PartitionSpec()),
                      mesh_mod.batch_sharding(mesh)),
        out_shardings=mesh_mod.batch_sharding(mesh))


def feed_consensus(has_data):
    """Global stop-consensus for synchronous training over an uneven feed.

    Every process calls this once per step with whether ITS feed produced a
    batch; returns True only while every process has data. The first dry
    process flips the whole cluster to stop on the same step, so sharded
    collectives never go ragged. This replaces the reference's heuristic of
    training only 90% of the per-worker steps to dodge uneven RDD partitions
    (reference: examples/mnist/keras/mnist_spark.py:58-64) with an exact
    consensus; the dropped remainder is bounded by the feed imbalance, and
    callers should df.terminate() to drain it.

    Callers MUST pair this with a bounded feed probe
    (``DataFeed.next_batch(bs, timeout=...)``), never a blocking read: a
    worker blocked in q.get() waiting for records that only arrive after its
    peers advance would never reach this collective, deadlocking the cluster
    until feed_timeout.

    Single-process clusters short-circuit (no collective). Cross-process it
    is one tiny allgather over the cluster fabric (Gloo on CPU hosts, ICI/DCN
    on TPU) per step.
    """
    import jax

    if jax.process_count() <= 1:
        return bool(has_data)
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([1 if has_data else 0], np.int32))
    return bool(np.asarray(flags).min())
