"""Pipeline parallelism: GPipe-style microbatch pipelining over the ``pp``
mesh axis, built from `shard_map` + `lax.ppermute` (net-new vs the reference,
which has no model parallelism — SURVEY.md §2.3).

Each device owns one stage's parameters (leading [n_stages] dim sharded over
pp).  Microbatches flow through the ring: at tick t, stage s processes
microbatch t-s and hands its activation to stage s+1 via a neighbor
ppermute (one ICI hop on a TPU torus).  The schedule runs
T = n_micro + n_stages - 1 ticks; bubbles are the standard GPipe overhead
(n_stages-1)/T.  The whole schedule is a `lax.scan`, so it is jit-compatible
and differentiable (ppermute's transpose is the reverse ppermute, giving the
correct backward pipeline automatically).

Composes with data parallelism: run under a mesh with dp>1 and shard the
microbatch batch dim over dp in `in_specs`.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage param pytrees into leaves with a leading
    [n_stages] dim (to be sharded over the pp axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def _pipeline_local(params, x, *, stage_fn, axis, n_micro):
    """shard_map-local body: `params` leaves are [1, ...] (this stage's
    slice); `x` is [n_micro, micro_batch, ...] (replicated over pp)."""
    n_stages = lax.psum(1, axis)
    stage_id = lax.axis_index(axis)
    local_params = jax.tree_util.tree_map(lambda p: p[0], params)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    y0 = stage_fn(local_params, x[0])
    out_shape = y0.shape  # stage output shape == stage input shape (residual nets)
    del y0

    def tick(carry, t):
        recv, outputs = carry
        x_t = lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
        inp = jnp.where(stage_id == 0, x_t, recv)
        y = stage_fn(local_params, inp)
        m = t - (n_stages - 1)
        is_last = stage_id == n_stages - 1
        updated = lax.dynamic_update_index_in_dim(
            outputs, y.astype(outputs.dtype),
            jnp.clip(m, 0, n_micro - 1), axis=0)
        outputs = jnp.where((m >= 0) & is_last, updated, outputs)
        recv_next = lax.ppermute(y, axis, perm)
        return (recv_next, outputs), None

    T = n_micro + n_stages - 1
    outputs = jnp.zeros((n_micro,) + tuple(out_shape), x.dtype)
    recv = jnp.zeros_like(x[0])
    (recv, outputs), _ = lax.scan(tick, (recv, outputs), jnp.arange(T))
    # Only the last stage holds real outputs; psum over pp replicates them
    # (other stages contribute zeros).
    return lax.psum(outputs, axis)


def pipeline_apply(stage_fn, stacked_params, x_micro, mesh, axis="pp",
                   batch_axes=("dp", "fsdp")):
    """Apply an N-stage pipeline.

    stage_fn(stage_params, x) -> y with y.shape == x.shape
    stacked_params: leaves [n_stages, ...] (see `stack_stage_params`)
    x_micro: [n_micro, micro_batch, ...]; micro_batch is sharded over
             `batch_axes` for dp composition.
    """
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu.parallel.ring_attention import _get_shard_map
    shard_map = _get_shard_map()

    n_micro = x_micro.shape[0]
    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    x_spec = P(None, batch_axes, *([None] * (x_micro.ndim - 2)))

    fn = functools.partial(_pipeline_local, stage_fn=stage_fn, axis=axis,
                           n_micro=n_micro)
    return shard_map(fn, mesh=mesh, in_specs=(param_specs, x_spec),
                     out_specs=x_spec, check_vma=False)(stacked_params, x_micro)
