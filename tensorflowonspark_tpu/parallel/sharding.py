"""Parameter-sharding rules: map parameter tree paths to PartitionSpecs.

The reference had no model parallelism (SURVEY.md §2.3 — "Model parallelism:
not implemented"); here it is first-class: a small rule engine assigns every
parameter a PartitionSpec by regex over its tree path, with Megatron-style
defaults for transformer blocks (column-parallel in-projections, row-parallel
out-projections) and optional fsdp sharding of whatever is left.
"""
import logging
import re

logger = logging.getLogger(__name__)


def P(*axes):
    from jax.sharding import PartitionSpec
    return PartitionSpec(*axes)


# Megatron-style defaults for transformer parameters.  Paths are
# '/'-joined flax param paths, matched with re.search.
# `(^|/)` anchors each pattern at a path-segment start so e.g. "router"
# cannot match an "out*" rule by substring.
DEFAULT_RULES = (
    # MoE expert weights first (most specific): leading expert dim over ep
    ((r"(^|/)experts_(wi|up)[^/]*/kernel"), ("ep", "embed", "tp")),
    ((r"(^|/)experts_(wo|down)[^/]*/kernel"), ("ep", "tp", "embed")),
    ((r"(^|/)router[^/]*/kernel"), ()),
    # attention in-projections: split heads over tp (column parallel)
    (r"(^|/)(query|key|value|qkv)[^/]*/kernel", ("embed", "tp")),
    # attention out-projection: row parallel (tp partial-sums -> psum)
    (r"(^|/)(out|o_proj|attn_out)[^/]*/kernel", ("tp", "embed")),
    # MLP up/gate: column parallel
    (r"(^|/)(mlp|ffn)[^/]*/(up|gate|wi|fc1|in_proj)[^/]*/kernel", ("embed", "tp")),
    # MLP down: row parallel
    (r"(^|/)(mlp|ffn)[^/]*/(down|wo|fc2|out_proj)[^/]*/kernel", ("tp", "embed")),
    # embedding tables: split the model dim over tp (vocab-dim sharding
    # would make the row gather a cross-shard collective)
    (r"(^|/)(embed|embedding|token_embed|pos_embed)[^/]*/(embedding|kernel)",
     ("embed", "tp")),
    # lm head: split vocab over tp; the loss reduces over vocab with a psum
    (r"(^|/)(lm_head|logits)[^/]*/kernel", ("embed", "tp")),
    # norms / biases / scales: replicated
    (r"(scale|bias|norm)", ()),
)

# Logical-axis name -> mesh axis (or None = replicate).  'ep' rides the dp
# axis: experts are distributed across data-parallel shards.
DEFAULT_AXIS_MAP = {
    "tp": "tp",
    "embed": None,
    "ep": "dp",
}


def spec_for_path(path, rules=DEFAULT_RULES, axis_map=None):
    """Return the PartitionSpec for one parameter path."""
    axis_map = axis_map or DEFAULT_AXIS_MAP
    for pattern, logical in rules:
        if re.search(pattern, path):
            return P(*(axis_map.get(ax) for ax in logical))
    return P()


def infer_param_shardings(params, mesh, rules=DEFAULT_RULES, axis_map=None,
                          fsdp=False):
    """Build a pytree of NamedShardings matching `params`.

    With fsdp=True, parameters that ended up replicated get their largest
    divisible dimension sharded over the fsdp axis (ZeRO-3 flavor).
    """
    import jax
    from jax.sharding import NamedSharding

    fsdp_size = mesh.shape.get("fsdp", 1)

    def one(path_parts, leaf):
        path = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path_parts)
        spec = spec_for_path(path, rules, axis_map)
        # Axes of extent 1 on this mesh carry no sharding but still trigger
        # sharding-in-types checks downstream — drop them.  Likewise drop a
        # mesh axis whose size doesn't divide the parameter dim (e.g. a GQA
        # kv-projection narrower than the tp degree): device_put on an
        # indivisible NamedSharding is an error, replication is just slower.
        shape = getattr(leaf, "shape", ())
        spec = P(*(
            ax if (ax is not None and mesh.shape.get(ax, 1) > 1
                   and i < len(shape) and shape[i] % mesh.shape[ax] == 0)
            else None
            for i, ax in enumerate(spec)))
        if fsdp and fsdp_size > 1:
            spec = _add_fsdp(spec, leaf, fsdp_size)
        # Drop specs that exceed the leaf's rank (scalar params etc.)
        if len(spec) > getattr(leaf, "ndim", 0):
            spec = P()
        while len(spec) and spec[-1] is None:
            spec = P(*spec[:-1])
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, params)


def _add_fsdp(spec, leaf, fsdp_size):
    """Shard the largest still-unsharded, divisible dim over fsdp."""
    ndim = getattr(leaf, "ndim", 0)
    shape = getattr(leaf, "shape", ())
    axes = list(spec) + [None] * (ndim - len(spec))
    candidates = [(shape[i], i) for i in range(ndim)
                  if axes[i] is None and shape[i] % fsdp_size == 0]
    if not candidates:
        return spec
    _, dim = max(candidates)
    axes[dim] = "fsdp"
    while axes and axes[-1] is None:
        axes.pop()
    return P(*axes)


def shard_params(params, shardings):
    """Place a parameter pytree onto the mesh per `shardings`."""
    import jax
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), params, shardings)
