"""Device-mesh construction and axis conventions.

Axis names (fixed vocabulary used by every sharding rule in the framework):

- ``dp``   — data parallel: batch is split, gradients allreduced (the
             TPU-native replacement for the reference's
             MultiWorkerMirroredStrategy path, SURVEY.md §2.3).
- ``fsdp`` — data parallel with parameter sharding (ZeRO-3 style): batch
             split like dp, parameters/optimizer state sharded and
             all-gathered per layer.
- ``pp``   — pipeline parallel: layers are partitioned into stages.
- ``tp``   — tensor parallel (Megatron-style): weight matrices split.
             Sequence parallelism (``sp``) reuses this axis: activations
             outside attention/mlp blocks are sharded over sequence on the
             same devices that shard weights.
- ``ep``   — expert parallel for MoE layers; experts are distributed over
             this axis (aliases a slice of the dp axis when not explicit).

Mesh-axis ORDER is (dp, fsdp, pp, tp): the innermost axis (tp) maps to the
most tightly-coupled devices (same host / shortest ICI hops), which is what
`jax.make_mesh` optimizes for; dp/fsdp collectives tolerate longer paths and
DCN when multi-slice.
"""
import dataclasses
import logging

logger = logging.getLogger(__name__)

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_PP = "pp"
AXIS_TP = "tp"
ALL_AXES = (AXIS_DP, AXIS_FSDP, AXIS_PP, AXIS_TP)

# Axes over which a data batch is split (used for per-host feed sharding and
# for gradient psum).
BATCH_AXES = (AXIS_DP, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout.  -1 for dp means "whatever is left"."""
    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    tp: int = 1

    def resolve(self, num_devices):
        fixed = self.fsdp * self.pp * self.tp
        if self.dp == -1:
            if num_devices % fixed != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fsdp*pp*tp={fixed}")
            dp = num_devices // fixed
        else:
            dp = self.dp
        total = dp * fixed
        if total != num_devices:
            raise ValueError(
                f"mesh {dp}x{self.fsdp}x{self.pp}x{self.tp}={total} does not "
                f"match {num_devices} devices")
        return MeshSpec(dp=dp, fsdp=self.fsdp, pp=self.pp, tp=self.tp)

    @property
    def shape(self):
        return (self.dp, self.fsdp, self.pp, self.tp)

    @property
    def batch_size_divisor(self):
        return self.dp * self.fsdp


def _axis_types_kwargs():
    """Auto axis types = classic GSPMD propagation: the compiler may insert
    collectives (partial-sum allreduce for row-parallel matmuls,
    reduce-scatter/all-gather at SP boundaries) instead of treating
    shardings as assertions, which is what Megatron-style TP+SP needs.
    Older jax has no AxisType — there Auto/GSPMD propagation is the only
    behavior, so passing nothing means the same thing."""
    import jax

    axis_type = getattr(jax.sharding, "AxisType", None)
    if axis_type is None:
        return {}
    return {"axis_types": (axis_type.Auto,) * len(ALL_AXES)}


def build_mesh(spec=None, devices=None):
    """Build a `jax.sharding.Mesh` with the framework's canonical axes."""
    import jax
    import numpy as np

    devs = list(devices) if devices is not None else jax.devices()
    spec = (spec or MeshSpec()).resolve(len(devs))
    kw = _axis_types_kwargs()
    if devices is None and hasattr(jax, "make_mesh"):
        # make_mesh picks a device order that keeps inner axes on short ICI
        # paths — use it whenever we're not given an explicit device list.
        mesh = jax.make_mesh(spec.shape, ALL_AXES, **kw)
    else:
        mesh = jax.sharding.Mesh(
            np.asarray(devs).reshape(spec.shape), ALL_AXES, **kw)
    logger.info("built mesh %s over %d devices", dict(zip(ALL_AXES, spec.shape)),
                len(devs))
    return mesh


def detect_num_slices(devices):
    """Number of distinct TPU slices in `devices` (1 when the platform does
    not expose ``slice_index``, e.g. CPU or single-slice TPU)."""
    idx = {getattr(d, "slice_index", 0) for d in devices}
    return len(idx)


def hybrid_device_array(spec, devices, num_slices):
    """Arrange `devices` into a (dp, fsdp, pp, tp) array where the slice
    (DCN granule) index varies only along the OUTERMOST part of dp.

    dp is factored as (num_slices, dp_inner): data-parallel gradient
    allreduce is the only collective that crosses slice boundaries and rides
    DCN; fsdp/pp/tp (and dp_inner) collectives stay on intra-slice ICI.
    Devices are grouped by ``slice_index`` when the platform exposes it,
    else by contiguous equal partitions of the given order.
    """
    import numpy as np

    if spec.dp % num_slices != 0:
        raise ValueError(
            f"dp={spec.dp} must be divisible by num_slices={num_slices} "
            "(the dp axis is the only one that crosses DCN)")
    per_slice = len(devices) // num_slices
    if per_slice * num_slices != len(devices):
        raise ValueError(f"{len(devices)} devices not divisible into "
                         f"{num_slices} slices")
    dp_inner = spec.dp // num_slices
    groups = {}
    if all(hasattr(d, "slice_index") for d in devices):
        for d in devices:
            groups.setdefault(d.slice_index, []).append(d)
        if len(groups) != num_slices:
            raise ValueError(
                f"devices span {len(groups)} slices, expected {num_slices}")
        try:
            # Real sliced hardware: let jax pick the ICI-optimal order
            # within each slice (physical-coordinate aware), with slices
            # laid along the outer dp factor.
            from jax.experimental import mesh_utils
            return mesh_utils.create_hybrid_device_mesh(
                (dp_inner, spec.fsdp, spec.pp, spec.tp),
                (num_slices, 1, 1, 1), devices)
        except (ValueError, ImportError, AttributeError) as e:
            # Topology-assignment ValueErrors (e.g. a per-slice shape that
            # doesn't map onto the physical torus) or devices jax can't
            # introspect: the enumeration-order placement below still
            # yields a working mesh with the slice/dp invariant intact.
            logger.warning("create_hybrid_device_mesh failed for platform "
                           "%s (%s); using enumeration-order placement",
                           getattr(devices[0], "platform", "?"), e)
    else:
        for i in range(num_slices):
            groups[i] = list(devices[i * per_slice:(i + 1) * per_slice])
    slice_arrays = []
    for key in sorted(groups):
        grp = groups[key]
        if len(grp) != per_slice:
            raise ValueError(f"slice {key} has {len(grp)} devices, "
                             f"expected {per_slice}")
        slice_arrays.append(
            np.asarray(grp, dtype=object).reshape(
                (dp_inner, spec.fsdp, spec.pp, spec.tp)))
    return np.concatenate(slice_arrays, axis=0)


def build_hybrid_mesh(spec=None, devices=None, num_slices="auto"):
    """Build a multi-slice (ICI x DCN) `jax.sharding.Mesh`.

    Same canonical axes as `build_mesh`, but device placement is
    slice-aware: the outer factor of the dp axis spans slices (DCN) while
    fsdp/pp/tp and the inner dp factor stay within a slice (ICI).  This is
    the TPU-native analog of the reference's multi-worker scaling story
    (gRPC ring across hosts, SURVEY.md §2.4): the only cross-slice traffic
    is the per-step gradient allreduce, which tolerates DCN latency.

    ``num_slices="auto"`` (the default) detects slices from the devices'
    ``slice_index`` and degrades to plain single-slice placement whenever
    the request cannot factor over them (dp not divisible by the slice
    count, or a ragged/truncated device list), so it is always safe to
    call.  Pass an explicit ``num_slices`` to force slice-aware placement
    (raising on impossible factorings) or to emulate slices on platforms
    without ``slice_index`` via contiguous grouping (the CPU-mesh tests).
    """
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    spec = (spec or MeshSpec()).resolve(len(devs))
    arr = None
    if num_slices == "auto":
        num_slices = detect_num_slices(devs)
        if num_slices > 1:
            try:
                arr = hybrid_device_array(spec, devs, num_slices)
            except ValueError as e:
                # single source of factorability rules: hybrid_device_array
                logger.warning("cannot factor mesh %s over %d slices (%s); "
                               "using single-slice placement",
                               spec.shape, num_slices, e)
                num_slices = 1
    if num_slices == 1:
        return build_mesh(spec, devices=devices)
    if arr is None:
        arr = hybrid_device_array(spec, devs, num_slices)
    mesh = jax.sharding.Mesh(arr, ALL_AXES, **_axis_types_kwargs())
    logger.info("built hybrid mesh %s over %d devices in %d slices",
                dict(zip(ALL_AXES, spec.shape)), len(devs), num_slices)
    return mesh


def local_mesh_spec(num_devices=None, tp=1, pp=1, fsdp=1):
    """Convenience: all remaining devices to dp."""
    import jax
    n = num_devices or len(jax.devices())
    return MeshSpec(dp=-1, fsdp=fsdp, pp=pp, tp=tp).resolve(n)


def batch_sharding(mesh):
    """NamedSharding for a [batch, ...] input: batch split over whichever
    of dp/fsdp the mesh actually has (a partial mesh — e.g. fsdp-only in
    tests or tp-only serving meshes — must not name absent axes)."""
    import jax
    P = jax.sharding.PartitionSpec
    axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
    return jax.sharding.NamedSharding(mesh, P(axes if axes else None))


def replicated_sharding(mesh):
    import jax
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def put_batch(tree, sharding):
    """Place a process-local batch (pytree of host arrays) onto the mesh.

    Single-process: a plain ``jax.device_put``. Multi-process SPMD: each
    process passes ITS shard and the result is the global array spanning all
    processes (``jax.make_array_from_process_local_data``) — the device_put
    analog of the reference's per-worker feed shards flowing into a
    collective-synchronized step. Every process must contribute the same
    local batch shape; pad the ragged tail (see examples/mnist) to keep the
    jitted step's shapes static.
    """
    import jax

    if jax.process_count() <= 1:
        return jax.device_put(tree, sharding)
    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            tree)
    # pytree of shardings matching the batch structure
    return jax.tree_util.tree_map(
        lambda x, s: jax.make_array_from_process_local_data(s, x),
        tree, sharding)
