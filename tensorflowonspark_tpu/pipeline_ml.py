"""Spark ML pipeline integration: true `pyspark.ml` Estimator/Model.

The reference's headline pipeline capability is that `TFEstimator` /
`TFModel` ARE Spark ML stages (`class TFEstimator(Estimator, TFParams...)`,
reference: pipeline.py:351,435) and therefore compose in
`Pipeline([...]).fit()` chains with param propagation.  The base
`tensorflowonspark_tpu.pipeline` module keeps its no-pyspark-required
API; this module is the import-gated Spark ML face over the same logic.

Importable whenever `pyspark.ml` is (real pyspark, or the in-repo
`minispark` test double after `minispark.install()` — same API).

    from tensorflowonspark_tpu.pipeline_ml import TFEstimator, TFModel
    model = Pipeline(stages=[est]).fit(df).stages[0]
    preds = model.transform(df)          # DataFrame of output columns
"""
import logging

from pyspark.ml import Estimator, Model

from . import export as export_mod
from . import pipeline as base

logger = logging.getLogger(__name__)


class TFEstimator(Estimator, base.TFParams):
    """Spark ML estimator: `fit(df)` runs a cluster over the DataFrame and
    returns a `TFModel` stage (maps reference TFEstimator,
    pipeline.py:351-432)."""

    def __init__(self, train_fn, tf_args=None, export_fn=None):
        Estimator.__init__(self)
        base.TFParams.__init__(self)
        self.train_fn = train_fn
        self.export_fn = export_fn
        self.args = base.Namespace(tf_args if tf_args is not None else {})

    def _fit(self, dataset):
        inner = base.TFEstimator(self.train_fn, self.args,
                                 export_fn=self.export_fn)
        inner._paramMap = dict(self._paramMap)
        fitted = inner._fit(dataset)
        model = TFModel(fitted.args)
        model._paramMap = dict(self._paramMap)
        return model


class TFModel(Model, base.TFParams):
    """Spark ML model: `transform(df)` -> DataFrame of model outputs
    (maps reference TFModel, pipeline.py:435-644; the reference likewise
    returns a DataFrame of the OUTPUT columns)."""

    def __init__(self, tf_args=None):
        Model.__init__(self)
        base.TFParams.__init__(self)
        self.args = base.Namespace(tf_args if tf_args is not None else {})

    def _output_columns(self, args):
        """Output column names, in model-output order, honoring
        output_mapping (tensor name -> column name)."""
        serving_dir = args.export_dir or args.model_dir
        _, signature = export_mod.read_signature(serving_dir,
                                                 args.signature_def_key)
        outs = signature.get("outputs", ["output"])
        mapping = args.output_mapping or {}
        if mapping:
            outs = [o for o in outs if o in mapping]
        return [mapping.get(o, o) for o in outs]

    def _transform(self, dataset):
        from pyspark.sql import SparkSession

        args = self.merge_args_params()
        inner = base.TFModel(self.args)
        inner._paramMap = dict(self._paramMap)
        # box=False: boxing happens below in _as_row, AFTER the column
        # split — a single vector-valued output must stay ONE ArrayType
        # column, which a pre-boxed list row would splat into columns
        preds = inner._transform(dataset, box=False)
        columns = self._output_columns(args)
        if hasattr(preds, "mapPartitions"):     # RDD of prediction rows
            n_cols = len(columns)

            def _as_row(r):
                import numpy as np

                # a tuple = multi-output row; anything else (scalar OR
                # per-row vector, ndarray or list) is one column's value
                row = tuple(r) if isinstance(r, tuple) else (r,)
                if len(row) != n_cols:
                    raise ValueError(
                        f"model emitted {len(row)} outputs but the schema "
                        f"has {n_cols} columns {columns}")
                # serving emits numpy scalars/row views (the columnar fast
                # path); real pyspark's type inference needs python values
                # — box here, at the DataFrame boundary, per column
                return tuple(v.item() if isinstance(v, np.generic)
                             else v.tolist() if isinstance(v, np.ndarray)
                             else v for v in row)

            spark = SparkSession.builder.getOrCreate()
            return spark.createDataFrame(preds.map(_as_row), list(columns))
        # plain-list path (no Spark context): keep rows, as base does
        return preds
