"""Offline bulk-inference jobs: the TFoS data pump reborn at fleet scale.

TensorFlowOnSpark's core trick was pumping RDD partitions through
long-lived workers (DataFeed / ``mapPartitions``, ``InputMode.SPARK``):
the cluster manager split the input into partitions and each executor
streamed its partition's records through a resident model.  The serving
fleet is the modern version of those long-lived workers, so this module
rebuilds the pump on top of it: ``POST /v1/jobs`` names an input file,
the gateway shards it into **byte-offset partition splits** (the same
FileSplit contract Hadoop/Spark text input uses), and a pool of
JobRunner threads streams each partition's records through the fleet as
**batch-class** requests under the WFQ scheduler — interactive traffic
always wins.

Exactly-once contract
---------------------
Every record has a stable identity ``job_id/partition/offset`` (the
byte offset of the record in the input file).  Two mechanisms compose
into exactly-once *output*:

- **Structural**: each partition appends result lines to its own spool
  file and checkpoints ``{next_offset, out_bytes, ...}`` with an atomic
  tmp-file + ``os.replace`` rename every ``checkpoint_every`` records.
  A partition that reruns (replica death mid-dispatch, gateway restart,
  worker crash) first truncates its spool file back to the last durable
  ``out_bytes`` and re-reads the input from ``next_offset`` — results
  that were never checkpointed are re-derived, results that were are
  never re-emitted.
- **Fleet-side**: the record identity rides the request as its
  ``Idempotency-Key``, so a duplicate dispatch (the runner timed out
  and retried while the first attempt was still decoding) cancels the
  orphaned twin on the replica instead of double-generating.

Sampled records are pinned to a per-record seed derived from the record
key, so a re-dispatch after a crash produces byte-identical output.

Checkpoint format (``<jobs_dir>/<job_id>/``)::

    job.json            immutable spec + splits + records_total + state
    parts/<p>.json      {"next_offset": O, "out_bytes": B,
                         "done_n": D, "failed_n": F, "done": bool}
    parts/<p>.out       result lines for partition p (jsonl)
    output.jsonl        the merged result, renamed into place on
                        completion (absent until then)

``job.json`` is rewritten (same atomic rename) only on state
transitions, so a gateway that dies mid-job leaves ``state: running``
on disk and the next gateway's ``--jobs_dir`` rescan resumes the job
from the partition checkpoints.

Each result line is ``{"offset": O, "p": P, "outputs": [...]}`` (or
``"error"`` instead of ``"outputs"`` for a record that permanently
failed — malformed JSON, oversized, or rejected by every replica), so
output lines correspond 1:1 with input records, in input order within
each partition.
"""
import collections
import hashlib
import json
import logging
import os
import struct
import threading
import time
import uuid

from . import faults
from .metrics import Counters

logger = logging.getLogger(__name__)

TERMINAL_STATES = ("completed", "failed", "cancelled")
FORMATS = ("jsonl", "tfrecord")

MAX_PARTITIONS = 4096
# a single input record larger than this is recorded as a failed record
# (never buffered whole); jsonl scanning stays O(bound) per record
MAX_RECORD_BYTES = 1 << 20


class JobError(RuntimeError):
    """A job-level operational failure (spool I/O exhausted retries)."""


class _Drained(Exception):
    """No partition left to lease (internal control flow)."""


class _Interrupted(Exception):
    """Worker told to stop mid-partition: requeue without attempt
    penalty (gateway shutdown / job cancel, not a partition fault)."""


class _Permanent(Exception):
    """A record the fleet rejected as invalid (4xx): retrying cannot
    help, the record fails and the partition moves on."""


class _Transient(Exception):
    """A dispatch failure worth retrying (replica died, fleet
    saturated, no replica routable right now)."""


# ---------------------------------------------------------------------------
# partition splitting (TFoS / Hadoop FileSplit semantics)


def split_file(path, n_partitions, fmt="jsonl"):
    """Shard `path` into up to `n_partitions` byte ranges
    ``[(start, end), ...]`` covering the file.

    Jsonl follows the Hadoop text FileSplit contract: splits land at
    arbitrary byte offsets, and a partition owns exactly the records
    whose FIRST byte lies in ``[start, end)`` — the reader skips past
    the record straddling ``start`` (the previous partition reads it to
    completion) and reads through the record containing ``end - 1``.
    TFRecord frames cannot be resynced from an arbitrary offset, so
    splits are snapped to record boundaries via the file's index.
    """
    size = os.path.getsize(path)
    n = max(1, min(int(n_partitions), MAX_PARTITIONS))
    if size == 0:
        return [(0, 0)]
    if fmt == "tfrecord":
        return _split_tfrecord(path, size, n)
    step = -(-size // n)              # ceil: at most n ragged ranges
    return [(lo, min(lo + step, size)) for lo in range(0, size, step)]


def _split_tfrecord(path, size, n):
    from . import tfrecord

    payload_offs, _ = tfrecord.index_records(path)
    if not payload_offs:
        return [(0, 0)]
    frame_offs = [off - 12 for off in payload_offs]   # 12B frame header
    step = -(-size // n)
    bounds = [0]
    for k in range(1, n):
        target = k * step
        nxt = next((off for off in frame_offs if off >= target), size)
        if nxt > bounds[-1] and nxt < size:
            bounds.append(nxt)
    bounds.append(size)
    return list(zip(bounds[:-1], bounds[1:]))


def _iter_jsonl(path, start, end, max_record_bytes):
    """Yield ``(offset, next_offset, text)`` for every record owned by
    the split; ``text`` is None for an oversized record (the caller
    emits an error line so output stays 1:1 with input)."""
    with open(path, "rb") as f:
        if start == 0:
            f.seek(0)
        else:
            # the record straddling `start` belongs to the previous
            # partition: position after the newline that ends the
            # record owning byte start-1
            f.seek(start - 1)
            f.readline()
        pos = f.tell()
        while pos < end:
            line = f.readline(max_record_bytes + 1)
            if not line:
                break
            rec_off = pos
            oversized = len(line) > max_record_bytes
            if oversized and not line.endswith(b"\n"):
                while True:          # resync: skip the rest of the record
                    more = f.readline(1 << 20)
                    if not more or more.endswith(b"\n"):
                        break
            pos = f.tell()
            if oversized:
                yield rec_off, pos, None
                continue
            text = line.strip()
            if text:                 # blank lines are not records
                yield rec_off, pos, text.decode("utf-8", "replace")


def _iter_tfrecord(path, start, end, max_record_bytes):
    """Yield ``(offset, next_offset, text)`` TFRecord frames whose
    frame start lies in ``[start, end)`` (splits are already
    boundary-snapped, so ``start`` IS a frame start)."""
    with open(path, "rb") as f:
        f.seek(start)
        pos = start
        while pos < end:
            header = f.read(12)
            if len(header) < 12:
                break
            (length,) = struct.unpack("<Q", header[:8])
            nxt = pos + 12 + length + 4
            if length > max_record_bytes:
                f.seek(nxt)
                yield pos, nxt, None
            else:
                payload = f.read(length)
                f.seek(4, os.SEEK_CUR)           # skip payload CRC
                if len(payload) < length:
                    break
                yield pos, nxt, payload.decode("utf-8", "replace")
            pos = nxt


def iter_partition(path, start, end, fmt="jsonl",
                   max_record_bytes=MAX_RECORD_BYTES):
    """Yield ``(offset, next_offset, text)`` for one partition split.
    ``offset`` keys the record (``job_id/p/offset``), ``next_offset``
    is the durable resume point once the record's result is
    checkpointed."""
    faults.check("jobs.partition_read")
    it = _iter_tfrecord if fmt == "tfrecord" else _iter_jsonl
    return it(path, start, end, max_record_bytes)


def count_records(path, splits, fmt="jsonl"):
    """Total records across `splits` — the denominator for progress and
    ETA.  One sequential pass; no fault probe (counting happens at
    submit, before the job exists to retry)."""
    it = _iter_tfrecord if fmt == "tfrecord" else _iter_jsonl
    return sum(sum(1 for _ in it(path, s, e, MAX_RECORD_BYTES))
               for s, e in splits)


# ---------------------------------------------------------------------------
# records -> requests


def record_seed(key):
    """Deterministic per-record sampling seed: a crashed partition's
    re-dispatch must produce byte-identical output, so an unseeded
    sampled record is pinned to a seed derived from its identity."""
    digest = hashlib.sha256(key.encode()).digest()
    return int.from_bytes(digest[:4], "big") & 0x7FFFFFFF


def record_request(text, template, key):
    """Parse one input record into a ``:generate`` request body.

    A record is either a bare token-id list (sugar for
    ``{"inputs": [<list>]}``) or a JSON object merged OVER the job's
    request template (record fields win).  The merge must resolve to a
    non-empty ``inputs``; anything else is a permanently failed record,
    not a job failure.
    """
    try:
        obj = json.loads(text)
    except ValueError as e:
        raise ValueError(f"record is not JSON: {e}")
    if isinstance(obj, list):
        obj = {"inputs": [obj]}
    if not isinstance(obj, dict):
        raise ValueError("record must be a JSON object or token-id list")
    req = dict(template or {})
    req.update(obj)
    if not req.get("inputs"):
        raise ValueError("record resolves to empty 'inputs'")
    req["priority"] = "batch"        # jobs NEVER compete as interactive
    req.pop("stream", None)          # spool files want the one-shot path
    if (float(req.get("temperature") or 0.0) > 0
            and req.get("seed") is None):
        req["seed"] = record_seed(key)
    return req


# ---------------------------------------------------------------------------
# the job record


class Job:
    """One bulk job: immutable spec + in-memory progress.  All mutable
    containers are guarded by the owning JobManager's lock."""

    def __init__(self, job_id, spec, jobdir):
        self.id = job_id
        self.spec = dict(spec)
        self.dir = jobdir
        self.input = spec["input"]
        self.fmt = spec.get("format") or "jsonl"
        self.model = spec.get("model") or "default"
        self.request = dict(spec.get("request") or {})
        self.tenant = spec.get("tenant") or "anonymous"
        self.trace_id = spec.get("trace")
        self.splits = [tuple(s) for s in spec["splits"]]
        self.records_total = int(spec["records_total"])
        self.workers = int(spec.get("workers") or 0)
        self.output = os.path.join(jobdir, "output.jsonl")
        self.state = spec.get("state") or "running"
        self.error = spec.get("error")
        self.halt = threading.Event()      # cancel/failure -> workers out
        # progress (JobManager._lock guards every access)
        self.pending = collections.deque()
        self.leased = set()
        self.done = set()
        self.attempts = {}                 # p -> failed attempts
        self.durable = {}                  # p -> [done_n, failed_n] (ckpt)
        self.live = {}                     # p -> [done, failed] since ckpt
        self.rate = collections.deque(maxlen=128)   # completion stamps

    def counts(self):
        """(records_done, records_failed) — durable + in-flight deltas.
        Caller holds the manager lock."""
        done = sum(v[0] for v in self.durable.values())
        fail = sum(v[1] for v in self.durable.values())
        done += sum(v[0] for v in self.live.values())
        fail += sum(v[1] for v in self.live.values())
        return done, fail


# ---------------------------------------------------------------------------
# the manager


class JobManager:
    """Owns the spool directory, the per-job runner threads, and the
    dispatch of partition records into the fleet.

    ``gateway`` wires dispatch through a live :class:`fleet.Gateway`
    (quota admission, WFQ batch-class routing, breaker accounting).
    ``dispatch`` replaces it with a callable ``(body, key) -> response``
    for benches and tests that drive an engine directly.
    """

    def __init__(self, jobs_dir, gateway=None, dispatch=None,
                 default_workers=2, checkpoint_every=16,
                 record_timeout_s=60.0, record_attempts=4,
                 partition_attempts=3, ckpt_attempts=4,
                 default_partitions=4, max_record_bytes=MAX_RECORD_BYTES,
                 counters=None, trace=None):
        self.jobs_dir = os.path.abspath(jobs_dir)
        os.makedirs(self.jobs_dir, exist_ok=True)
        self._gw = gateway
        self._dispatch_fn = dispatch
        self.default_workers = max(1, int(default_workers))
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.record_timeout_s = float(record_timeout_s or 60.0)
        self.record_attempts = max(1, int(record_attempts))
        self.partition_attempts = max(1, int(partition_attempts))
        self.ckpt_attempts = max(1, int(ckpt_attempts))
        self.default_partitions = max(1, int(default_partitions))
        self.max_record_bytes = int(max_record_bytes)
        self.counters = counters if counters is not None else Counters()
        self.trace = trace
        self._lock = threading.Lock()
        self._jobs = {}
        self._threads = []
        self._stop = threading.Event()

    # ---- spool I/O (atomic rename + bounded retry) -------------------

    def _spool_write(self, path, obj):
        """Atomic JSON write: tmp + fsync + rename, retried a bounded
        number of times.  Exhausting the retries raises JobError — the
        caller's partition is abandoned rather than marked durable."""
        last = None
        for i in range(self.ckpt_attempts):
            try:
                faults.check("jobs.checkpoint_write")
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(obj, f, sort_keys=True)
                    f.write("\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                return
            except OSError as e:
                last = e
                self.counters.inc("jobs_ckpt_retries")
                time.sleep(min(0.02 * (1 << i), 0.25))
        raise JobError(f"spool write {path} failed after "
                       f"{self.ckpt_attempts} attempts: {last}")

    @staticmethod
    def _parts_dir(job):
        return os.path.join(job.dir, "parts")

    def _ckpt_path(self, job, p):
        return os.path.join(self._parts_dir(job), f"{p}.json")

    def _part_path(self, job, p):
        return os.path.join(self._parts_dir(job), f"{p}.out")

    def _load_ckpt(self, job, p):
        try:
            with open(self._ckpt_path(job, p), encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"next_offset": job.splits[p][0], "out_bytes": 0,
                    "done_n": 0, "failed_n": 0, "done": False}

    def _persist_state(self, job):
        """Best-effort durable state transition (job.json rewrite).  A
        persistent spool fault leaves the durable state behind the
        in-memory one; a later rescan then re-drives from checkpoints,
        which is idempotent by construction."""
        with self._lock:
            spec = dict(job.spec, state=job.state, error=job.error)
            job.spec = spec
        try:
            self._spool_write(os.path.join(job.dir, "job.json"), spec)
        except JobError as e:
            logger.error("job %s: state persist failed: %s", job.id, e)

    # ---- submit / rescan / status ------------------------------------

    def submit(self, spec, tenant="anonymous"):
        """Validate, split, count, persist, and start one job.  Returns
        the initial status dict (also the ``POST /v1/jobs`` body)."""
        if self._stop.is_set():
            raise JobError("job manager is stopping")
        if not isinstance(spec, dict):
            raise ValueError("job spec must be a JSON object")
        path = spec.get("input")
        if not path or not isinstance(path, str):
            raise ValueError("job spec wants 'input': path to a record "
                             "file readable by the gateway")
        if not os.path.isfile(path):
            raise ValueError(f"input {path!r} is not a readable file")
        fmt = spec.get("format") or "jsonl"
        if fmt not in FORMATS:
            raise ValueError(f"format {fmt!r} not one of {FORMATS}")
        request = spec.get("request") or {}
        if not isinstance(request, dict):
            raise ValueError("'request' template must be an object")
        n_parts = spec.get("partitions")
        n_parts = (self.default_partitions if n_parts is None
                   else int(n_parts))
        if n_parts < 1:
            raise ValueError("'partitions' must be >= 1")
        workers = int(spec.get("workers") or self.default_workers)
        trace_id = spec.get("trace")
        splits = split_file(path, n_parts, fmt=fmt)
        total = count_records(path, splits, fmt=fmt)
        job_id = uuid.uuid4().hex[:12]
        jobdir = os.path.join(self.jobs_dir, job_id)
        jspec = {"id": job_id, "input": os.path.abspath(path),
                 "format": fmt, "model": spec.get("model") or "default",
                 "request": request, "tenant": tenant,
                 "trace": trace_id if trace_id else None,
                 "workers": workers, "splits": [list(s) for s in splits],
                 "records_total": total, "state": "running",
                 "error": None, "created_s": time.time()}
        os.makedirs(os.path.join(jobdir, "parts"), exist_ok=True)
        # durable BEFORE visible: a gateway crash between these writes
        # leaves a complete job.json that rescan resumes, never a half
        # job that dispatched records with no checkpoint home
        self._spool_write(os.path.join(jobdir, "job.json"), jspec)
        job = Job(job_id, jspec, jobdir)
        with self._lock:
            job.pending.extend(range(len(splits)))
            self._jobs[job_id] = job
        self.counters.inc("jobs_submitted")
        if self.trace is not None:
            self.trace.event(job.trace_id, "job.submit", job=job_id,
                             partitions=len(splits), records=total)
        self._start_workers(job)
        return self.status(job_id)

    def rescan(self):
        """Load every job under ``jobs_dir``; resume the incomplete
        ones from their partition checkpoints (the gateway-restart
        survival path).  Returns the resumed job ids."""
        resumed = []
        try:
            names = sorted(os.listdir(self.jobs_dir))
        except OSError:
            return resumed
        for name in names:
            jobdir = os.path.join(self.jobs_dir, name)
            jf = os.path.join(jobdir, "job.json")
            if not os.path.isfile(jf):
                continue
            with self._lock:
                known = name in self._jobs
            if known:
                continue
            try:
                with open(jf, encoding="utf-8") as f:
                    jspec = json.load(f)
            except (OSError, ValueError) as e:
                logger.warning("jobs rescan: unreadable %s: %s", jf, e)
                continue
            job = Job(jspec.get("id") or name, jspec, jobdir)
            # fold durable per-partition progress back in
            for p in range(len(job.splits)):
                ck = self._load_ckpt(job, p)
                job.durable[p] = [int(ck.get("done_n") or 0),
                                  int(ck.get("failed_n") or 0)]
                if ck.get("done"):
                    job.done.add(p)
            with self._lock:
                if job.state == "running":
                    job.pending.extend(
                        p for p in range(len(job.splits))
                        if p not in job.done)
                self._jobs[job.id] = job
            if job.state != "running":
                continue
            if not os.path.isfile(job.input):
                job.state = "failed"
                job.error = f"input {job.input!r} vanished across restart"
                self._persist_state(job)
                continue
            resumed.append(job.id)
            self.counters.inc("jobs_resumed")
            self._start_workers(job)
        return resumed

    def _get(self, job_id):
        with self._lock:
            job = self._jobs.get(str(job_id))
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        return job

    def status(self, job_id):
        """The ``GET /v1/jobs/<id>`` body: progress + drain-rate ETA."""
        job = self._get(job_id)
        with self._lock:
            done, failed = job.counts()
            stamps = list(job.rate)
            out = {"id": job.id, "state": job.state, "error": job.error,
                   "input": job.input, "format": job.fmt,
                   "model": job.model, "tenant": job.tenant,
                   "partitions": len(job.splits),
                   "partitions_done": len(job.done),
                   "records_total": job.records_total,
                   "records_done": done, "records_failed": failed,
                   "output": (job.output if job.state == "completed"
                              else None)}
        # drain-rate ETA, same estimator shape as the gateway's
        # Retry-After: completions/s over a recent window
        rate = 0.0
        if len(stamps) >= 2 and stamps[-1] > stamps[0]:
            rate = (len(stamps) - 1) / (stamps[-1] - stamps[0])
        remaining = max(0, out["records_total"] - done - failed)
        out["records_per_s"] = round(rate, 3)
        out["eta_s"] = (round(remaining / rate, 1)
                        if rate > 0 and out["state"] == "running"
                        else None)
        return out

    def list(self):
        with self._lock:
            ids = sorted(self._jobs)
        return [self.status(i) for i in ids]

    def stats(self):
        """Summable keys for the gateway's fleet totals (and thereby
        ``/metrics``): active jobs + record progress across all known
        jobs this gateway life."""
        with self._lock:
            jobs = list(self._jobs.values())
            active = sum(1 for j in jobs if j.state == "running")
            done = failed = 0
            for j in jobs:
                d, f = j.counts()
                done += d
                failed += f
        return {"jobs_active": active, "jobs_records_done": done,
                "jobs_records_failed": failed}

    def cancel(self, job_id):
        """Teardown: halt the runners, persist the terminal state.  A
        repeat cancel (or cancel of a finished job) is a no-op that
        returns the terminal status."""
        job = self._get(job_id)
        with self._lock:
            terminal = job.state in TERMINAL_STATES
            if not terminal:
                job.state = "cancelled"
        if not terminal:
            job.halt.set()
            self._persist_state(job)
            self.counters.inc("jobs_cancelled")
            if self.trace is not None:
                self.trace.event(job.trace_id, "job.cancel", job=job.id)
        return self.status(job_id)

    def stop(self, timeout_s=10.0):
        """Halt every runner WITHOUT marking jobs terminal: durable
        state stays ``running`` so the next gateway's rescan resumes
        from the checkpoints (this is the restart path, not cancel)."""
        self._stop.set()
        with self._lock:
            threads = list(self._threads)
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    # ---- runners -----------------------------------------------------

    def _start_workers(self, job):
        with self._lock:
            n_pending = len(job.pending)
        n = min(max(1, job.workers or self.default_workers),
                max(1, n_pending))
        if n_pending == 0:
            n = 1                     # one worker to notice completion
        for k in range(n):
            t = threading.Thread(target=self._worker, args=(job,),
                                 name=f"job-{job.id}-w{k}", daemon=True)
            with self._lock:
                self._threads.append(t)
            t.start()

    def _worker(self, job):
        try:
            while not self._stop.is_set():
                try:
                    lease = self._lease_partition(job)
                except _Drained:
                    break
                try:
                    self._run_partition(job, lease)
                except BaseException as e:
                    self._abandon_partition(lease, e)
                else:
                    self._commit_partition(lease)
            self._maybe_finish(job)
        except Exception:
            logger.exception("job %s: worker died", job.id)

    def _lease_partition(self, job):
        """Claim the next pending partition for this worker.  The lease
        MUST be returned through :meth:`_commit_partition` or
        :meth:`_abandon_partition` — graftcheck's lifecycle scan
        enforces exactly that pairing."""
        with self._lock:
            if (self._stop.is_set() or job.halt.is_set()
                    or job.state != "running" or not job.pending):
                raise _Drained()
            p = job.pending.popleft()
            job.leased.add(p)
        return {"job": job, "p": p, "t0": time.monotonic()}

    def _commit_partition(self, lease):
        job, p = lease["job"], lease["p"]
        with self._lock:
            job.leased.discard(p)
            job.done.add(p)
        if self.trace is not None:
            self.trace.span_at(job.trace_id, "job.partition",
                               lease["t0"], time.monotonic(),
                               job=job.id, partition=p, status="done")

    def _abandon_partition(self, lease, err=None):
        """Requeue a partition whose run did not complete.  A genuine
        fault costs an attempt; exhausting ``partition_attempts`` fails
        the JOB (a poisoned partition must not spin forever).  An
        interruption (shutdown, cancel) requeues penalty-free — the
        rerun is the resume path, not a retry."""
        job, p = lease["job"], lease["p"]
        interrupted = isinstance(err, _Interrupted)
        failed = False
        with self._lock:
            job.leased.discard(p)
            job.live.pop(p, None)     # un-checkpointed deltas roll back
            job.pending.append(p)
            if not interrupted:
                n = job.attempts.get(p, 0) + 1
                job.attempts[p] = n
                if n >= self.partition_attempts and job.state == "running":
                    job.state = "failed"
                    job.error = (f"partition {p} failed "
                                 f"{n} attempts: {err}")
                    failed = True
        if self.trace is not None:
            self.trace.span_at(job.trace_id, "job.partition",
                               lease["t0"], time.monotonic(),
                               job=job.id, partition=p,
                               status="interrupted" if interrupted
                               else "abandoned")
        if not interrupted:
            logger.warning("job %s: partition %d abandoned: %s",
                           job.id, p, err)
        if failed:
            job.halt.set()
            self._persist_state(job)
            self.counters.inc("jobs_failed")

    def _run_partition(self, job, lease):
        p = lease["p"]
        start, end = job.splits[p]
        ck = self._load_ckpt(job, p)
        if ck.get("done"):
            return
        os.makedirs(self._parts_dir(job), exist_ok=True)
        out = open(self._part_path(job, p), "ab")
        try:
            # everything past the last durable byte came from dispatches
            # that never checkpointed; re-deriving them (below) is what
            # makes the output exactly-once across crashes
            out.truncate(int(ck.get("out_bytes") or 0))
            n_since = 0
            for off, nxt, text in iter_partition(
                    job.input, start, end, fmt=job.fmt,
                    max_record_bytes=self.max_record_bytes):
                if off < int(ck.get("next_offset") or 0):
                    continue          # durable already
                if self._stop.is_set() or job.halt.is_set():
                    raise _Interrupted("halted mid-partition")
                out.write(self._score_record(job, p, off, text))
                ck["next_offset"] = nxt
                n_since += 1
                if n_since >= self.checkpoint_every:
                    self._checkpoint(job, p, out, ck)
                    n_since = 0
            ck["done"] = True
            self._checkpoint(job, p, out, ck)
        finally:
            out.close()

    def _checkpoint(self, job, p, out, ck):
        """Make the partition's spool durable, then the checkpoint that
        points at it — strictly in that order, so a crash between the
        two re-derives records instead of losing them."""
        out.flush()
        os.fsync(out.fileno())
        ck["out_bytes"] = os.fstat(out.fileno()).st_size
        with self._lock:
            live = job.live.pop(p, [0, 0])
            ck["done_n"] = int(ck.get("done_n") or 0) + live[0]
            ck["failed_n"] = int(ck.get("failed_n") or 0) + live[1]
            job.durable[p] = [ck["done_n"], ck["failed_n"]]
        self._spool_write(self._ckpt_path(job, p), ck)

    def _score_record(self, job, p, off, text):
        """One record end to end: parse, dispatch (with retry), account.
        Returns the result line (bytes).  Raises only for partition-level
        trouble (interruption, undeliverable record)."""
        key = f"{job.id}/{p}/{off}"
        err = None
        outs = None
        if text is None:
            err = f"record exceeds {self.max_record_bytes} bytes"
        else:
            try:
                body = record_request(text, job.request, key)
            except ValueError as e:
                err = str(e)
            else:
                try:
                    outs = self._dispatch(job, body, key)
                except _Permanent as e:
                    err = str(e)
        if self.trace is not None and off == job.splits[p][0]:
            # one sample span per partition keeps the ring useful
            # without a million-record job flooding it
            self.trace.event(job.trace_id, "job.record", job=job.id,
                             partition=p, offset=off,
                             ok=err is None)
        with self._lock:
            live = job.live.setdefault(p, [0, 0])
            if err is None:
                live[0] += 1
                job.rate.append(time.monotonic())
            else:
                live[1] += 1
        self.counters.inc("jobs_records_done" if err is None
                          else "jobs_records_failed")
        obj = {"p": p, "offset": off}
        if err is None:
            obj["outputs"] = outs
        else:
            obj["error"] = err
        return (json.dumps(obj, sort_keys=True) + "\n").encode()

    # ---- dispatch ----------------------------------------------------

    def _dispatch(self, job, body, key):
        """Deliver one record to the fleet, retrying transient failures
        (replica death, saturation) across attempts.  Returns the
        outputs list, returns an error via _score_record for permanent
        rejections, and raises for an undeliverable record (the
        partition retries later, against a hopefully-healthier
        fleet)."""
        last = None
        for attempt in range(self.record_attempts):
            if self._stop.is_set() or job.halt.is_set():
                raise _Interrupted("halted mid-record")
            try:
                faults.check("jobs.record_dispatch")
                if self._dispatch_fn is not None:
                    resp = self._dispatch_fn(dict(body), key)
                else:
                    resp = self._dispatch_gateway(job, body, key)
                return resp.get("outputs")
            except _Permanent:
                raise
            except (OSError, _Transient) as e:
                last = e
                self.counters.inc("jobs_record_retries")
                job.halt.wait(min(0.05 * (1 << attempt), 1.0))
        raise JobError(f"record {key} undeliverable after "
                       f"{self.record_attempts} attempts: {last}")

    def _dispatch_gateway(self, job, body, key):
        """One batch-class exchange through the owning gateway: quota
        admission, WFQ-degraded routing, breaker accounting — the same
        envelope an external batch client gets, minus the HTTP hop."""
        from . import fleet            # deferred: fleet imports jobs
        gw = self._gw
        try:
            gw._quota_admit(job.tenant)
        except fleet.Saturated as e:
            raise _Transient(str(e))
        try:
            try:
                r = gw._choose_degraded(job.tenant, "batch",
                                        roles=("prefill", "mixed"))
            except (fleet.NoReplica, fleet.Saturated) as e:
                raise _Transient(str(e))
            try:
                conn, resp = gw._request(
                    r, "POST", f"/v1/models/{job.model}:generate",
                    body=json.dumps(body),
                    timeout=self.record_timeout_s,
                    headers={"Idempotency-Key": key,
                             "X-Tenant": job.tenant,
                             "X-Priority": "batch"})
                try:
                    status = resp.status
                    data = resp.read()
                finally:
                    conn.close()
            except OSError:
                gw._release(r, ok=False)
                raise
            # a 4xx is the replica judging the RECORD, not failing:
            # it must not trip the breaker, and retrying cannot help
            gw._release(r, ok=status == 200 or 400 <= status < 500)
            if status == 200:
                return json.loads(data)
            try:
                msg = json.loads(data).get("error") or f"status {status}"
            except ValueError:
                msg = f"status {status}"
            if 400 <= status < 500:
                raise _Permanent(f"replica {r.id}: {msg}")
            raise _Transient(f"replica {r.id}: {msg}")
        finally:
            gw._quota_release(job.tenant)

    # ---- completion --------------------------------------------------

    def _maybe_finish(self, job):
        """Last worker out merges the partition spools into the final
        output (atomic rename) and flips the durable state."""
        with self._lock:
            if (job.state != "running" or job.leased
                    or len(job.done) != len(job.splits)):
                return
            job.state = "completed"   # claimed under the lock: exactly
            n_parts = len(job.splits)  # one worker runs the merge
        try:
            tmp = job.output + ".tmp"
            with open(tmp, "wb") as dst:
                for p in range(n_parts):
                    try:
                        with open(self._part_path(job, p), "rb") as src:
                            while True:
                                chunk = src.read(1 << 20)
                                if not chunk:
                                    break
                                dst.write(chunk)
                    except FileNotFoundError:
                        pass          # an empty partition spooled nothing
                dst.flush()
                os.fsync(dst.fileno())
            os.replace(tmp, job.output)
        except OSError as e:
            with self._lock:
                job.state = "failed"
                job.error = f"output merge failed: {e}"
            self._persist_state(job)
            self.counters.inc("jobs_failed")
            return
        self._persist_state(job)
        self.counters.inc("jobs_completed")
        if self.trace is not None:
            self.trace.event(job.trace_id, "job.done", job=job.id,
                             output=job.output)
        logger.info("job %s: completed -> %s", job.id, job.output)
