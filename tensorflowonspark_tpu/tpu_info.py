"""Accelerator discovery & per-worker chip assignment (maps reference gpu_info.py:1-98).

The reference shells out to `nvidia-smi`, parses busy GPUs, and sets
CUDA_VISIBLE_DEVICES with retry/backoff.  On TPU the runtime owns device
enumeration, so the equivalents are:

- probing the JAX platform (with the same retry×backoff discipline, since a
  TPU chip can be transiently held by a dying predecessor process),
- deterministic per-worker chip slicing via ``TPU_VISIBLE_CHIPS`` when
  multiple executor processes share one TPU host (the analog of the
  reference's worker-index-based GPU placement, gpu_info.py:60-87),
- topology metadata (slice shape, process index) for mesh construction.

All probing goes through `_probe_devices` so tests can mock the seam
(the reference tests patch `gpu_info.get_gpus`; SURVEY.md §4).
"""
import logging
import os
import time

logger = logging.getLogger(__name__)

MAX_RETRIES = 3
RETRY_DELAY_SECS = 10  # reference used 30s*retry; TPU probes are cheaper

AS_LIST = "list"
AS_STRING = "string"


def _probe_devices(platform=None):
    """Return jax.devices(platform) — isolated seam for mocking."""
    import jax
    return jax.devices(platform) if platform else jax.devices()


def is_tpu_available():
    """True if any TPU chip is visible (reference: gpu_info.py:22-28)."""
    try:
        return len(_probe_devices("tpu")) > 0
    except RuntimeError:
        return False


def get_accelerator_info():
    """Summarize the visible accelerator platform.

    Returns dict(platform, device_kind, num_devices, num_local_devices,
    process_index, num_processes).
    """
    import jax
    devices = _probe_devices()
    local = [d for d in devices if d.process_index == jax.process_index()]
    return {
        "platform": devices[0].platform if devices else "none",
        "device_kind": devices[0].device_kind if devices else "none",
        "num_devices": len(devices),
        "num_local_devices": len(local),
        "process_index": jax.process_index(),
        "num_processes": jax.process_count(),
    }


def _count_local_chips():
    """Count local TPU chips WITHOUT initializing the JAX runtime.

    Order matters: initializing JAX in this process would lock every chip
    (libtpu takes an exclusive lock at runtime init) and make a later
    ``TPU_VISIBLE_CHIPS`` restriction a no-op for this process.  So we count
    via env override, then devfs, and only fall back to a JAX probe (which is
    accurate but locks the chips — fine when this process is the one that
    will use them all anyway).
    """
    env = os.environ.get("TFOS_TPU_LOCAL_CHIPS")
    if env:
        return int(env)
    import glob
    accels = glob.glob("/dev/accel*") + glob.glob("/dev/vfio/[0-9]*")
    if accels:
        return len(accels)
    return len(_probe_devices())


def assign_chips(num_chips, worker_index=-1, fmt=AS_STRING):
    """Deterministically assign `num_chips` local chips to this worker.

    Maps reference gpu_info.get_gpus (gpu_info.py:31-98): when several worker
    processes land on one host, worker i takes chips
    [i*num_chips, (i+1)*num_chips); with worker_index < 0 assignment starts
    at 0.  Oversubscription raises — TPU chips are exclusively locked by the
    runtime, so silently sharing them (the reference wrapped GPU indices
    modulo the pool) would crash a sibling at init time instead.  Retries
    with linear backoff to ride out a predecessor process still holding the
    chips.

    Sets ``TPU_VISIBLE_CHIPS`` so a JAX runtime started AFTER this call (in
    this process or a child) sees only the assigned chips, and returns the
    chip ids as a comma string (AS_STRING) or list (AS_LIST).
    """
    num_local = None
    last_err = None
    for retry in range(MAX_RETRIES + 1):
        try:
            num_local = _count_local_chips()
            break
        except RuntimeError as e:
            last_err = e
            if retry < MAX_RETRIES:
                delay = RETRY_DELAY_SECS * (retry + 1)
                logger.warning("accelerator probe failed (%s); retrying in %ds", e, delay)
                time.sleep(delay)
    if num_local is None:
        raise RuntimeError(f"no accelerator devices available: {last_err}")

    if num_chips > num_local:
        raise RuntimeError(
            f"requested {num_chips} chips but only {num_local} visible")

    start = 0 if worker_index < 0 else worker_index * num_chips
    if start + num_chips > num_local:
        raise RuntimeError(
            f"worker {worker_index} needs chips [{start}, {start + num_chips}) "
            f"but only {num_local} exist on this host — oversubscription is "
            f"an error on TPU (chips are exclusively locked)")
    chip_ids = list(range(start, start + num_chips))
    visible = ",".join(str(c) for c in chip_ids)
    os.environ["TPU_VISIBLE_CHIPS"] = visible
    logger.info("worker %d assigned chips [%s] of %d local", worker_index, visible, num_local)
    return chip_ids if fmt == AS_LIST else visible


def get_slice_topology():
    """Best-effort TPU slice topology from env + runtime.

    Cloud TPU VMs export TPU_WORKER_ID / TPU_WORKER_HOSTNAMES; fall back to
    single-host when absent.  Returns dict(worker_id, num_workers, hosts).
    """
    hosts_env = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    hosts = [h for h in hosts_env.split(",") if h] or ["localhost"]
    worker_id = int(os.environ.get("TPU_WORKER_ID", "0"))
    return {"worker_id": worker_id, "num_workers": len(hosts), "hosts": hosts}
