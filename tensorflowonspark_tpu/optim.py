"""Optimizer & LR-schedule factory — the config surface training loops use.

The reference leaves optimizers to user TF code (Keras compile); here the
framework provides the standard TPU-training recipes behind one call so
examples, the pipeline Estimator, and user map_funs share them:

    opt, schedule = optim.make_optimizer(
        "adamw", learning_rate=3e-4, warmup_steps=1000,
        total_steps=100_000, schedule="cosine", weight_decay=0.1,
        clip_norm=1.0)

All knobs are plain config values (strings/numbers), so they pass through
`pipeline.Namespace`/argparse unchanged.
"""
import logging

logger = logging.getLogger(__name__)

SCHEDULES = ("constant", "cosine", "linear", "rsqrt")
OPTIMIZERS = ("adam", "adamw", "adamw_fused", "adamw8bit", "sgd", "lion",
              "lion_fused", "adafactor")
# single-pass Pallas kernels (ops/fused_optim): clipping/decay/lr fold INTO
# the fused update instead of an optax.chain around it
_FUSED = ("adamw_fused", "lion_fused")


def make_schedule(learning_rate, schedule="constant", warmup_steps=0,
                  total_steps=None, end_value=0.0):
    """An optax schedule: linear warmup into constant/cosine/linear/rsqrt
    decay.  `total_steps` is required for cosine/linear."""
    import optax

    if schedule not in SCHEDULES:
        raise ValueError(f"schedule={schedule!r} not in {SCHEDULES}")
    if schedule in ("cosine", "linear") and not total_steps:
        raise ValueError(f"schedule={schedule!r} requires total_steps")
    decay_steps = max((total_steps or 0) - warmup_steps, 1)
    if schedule == "constant":
        main = optax.constant_schedule(learning_rate)
    elif schedule == "cosine":
        main = optax.cosine_decay_schedule(learning_rate, decay_steps,
                                           alpha=end_value / learning_rate
                                           if learning_rate else 0.0)
    elif schedule == "linear":
        main = optax.linear_schedule(learning_rate, end_value, decay_steps)
    else:  # rsqrt (the classic transformer schedule tail)
        shift = max(warmup_steps, 1)

        def main(step):
            return learning_rate * (shift ** 0.5) / ((step + shift) ** 0.5)
    if warmup_steps:
        warm = optax.linear_schedule(0.0, learning_rate, warmup_steps)
        return optax.join_schedules([warm, main], [warmup_steps])
    return main


def make_optimizer(name="adamw", learning_rate=1e-3, schedule="constant",
                   warmup_steps=0, total_steps=None, end_value=0.0,
                   weight_decay=0.0, clip_norm=None, b1=None, b2=None,
                   momentum=0.9, decay_mask=None, mu_dtype=None,
                   layouts=None):
    """Build `(optax_optimizer, schedule_fn)` from plain config values.

    `decay_mask` (a pytree-of-bools fn or tree) routes weight decay away
    from biases/norms the usual way, e.g.
    ``lambda p: jax.tree.map(lambda x: x.ndim >= 2, p)``.
    `clip_norm` prepends global-norm gradient clipping.  `b1`/`b2`
    default to each optimizer's own published defaults (adam/adamw
    0.9/0.999, lion 0.9/0.99).  Optimizers without a weight-decay knob
    (adam, sgd, adafactor) refuse a nonzero `weight_decay` rather than
    silently dropping it.

    `mu_dtype` (adam/adamw/lion and the fused variants) stores the first
    moment in a narrower dtype — ``"bfloat16"`` halves that state's HBM
    footprint AND the optimizer update's bandwidth (momentum is
    noise-tolerant; the second moment stays float32).  On one v5e chip
    this took the 0.87B flagship-LM step from 351 ms (61.8% MFU) to
    326 ms (66.6% MFU, the canonical bench.py run); see BASELINE.md
    round 3.

    ``adamw_fused`` / ``lion_fused`` run the whole update — clip scale,
    moments, decay, lr — as ONE Pallas pass per parameter block
    (ops/fused_optim.py): `clip_norm` folds in as a pre-computed scalar
    instead of a chained transform, and the returned object carries an
    extra single-pass ``apply(grads, state, params)`` the train-step
    harness uses automatically.  Same math as the optax references
    (tests assert step-for-step parity); fewest HBM passes of any
    optimizer here — the SPEED choice, vs adamw8bit (memory).
    """
    import optax

    if isinstance(mu_dtype, str):
        import jax.numpy as jnp
        mu_dtype = jnp.dtype(mu_dtype)
    if mu_dtype is not None and name not in ("adam", "adamw", "lion") + _FUSED:
        raise ValueError(f"optimizer={name!r} has no mu_dtype knob")
    if layouts is not None and name != "adamw8bit":
        raise ValueError(
            f"optimizer={name!r} has no quantized-state layouts knob "
            "(layouts= is adamw8bit-only; see optim8bit.layouts_for_shardings)")

    if name not in OPTIMIZERS:
        raise ValueError(f"optimizer={name!r} not in {OPTIMIZERS}")
    if (weight_decay or decay_mask is not None) and name not in (
            "adamw", "adamw8bit", "lion") + _FUSED:
        raise ValueError(
            f"optimizer={name!r} has no decoupled weight decay; use adamw, "
            "adamw_fused, adamw8bit, or lion (or drop "
            "weight_decay/decay_mask)")
    sched = make_schedule(learning_rate, schedule, warmup_steps,
                          total_steps, end_value)
    if name == "adam":
        core = optax.adam(sched, b1=b1 or 0.9, b2=b2 or 0.999,
                          mu_dtype=mu_dtype)
    elif name == "adamw":
        core = optax.adamw(sched, b1=b1 or 0.9, b2=b2 or 0.999,
                           weight_decay=weight_decay, mask=decay_mask,
                           mu_dtype=mu_dtype)
    elif name in _FUSED:
        # single-pass Pallas kernels: clip_norm and decay fold INTO the
        # fused update (chaining optax.clip around them would both waste
        # a pass and strip the .apply method the train step fuses on)
        from tensorflowonspark_tpu.ops import fused_optim
        if name == "adamw_fused":
            core = fused_optim.adamw_fused(
                sched, b1=b1 or 0.9, b2=b2 or 0.999,
                weight_decay=weight_decay, mask=decay_mask,
                clip_norm=clip_norm, mu_dtype=mu_dtype)
        else:
            core = fused_optim.lion_fused(
                sched, b1=b1 or 0.9, b2=b2 or 0.99,
                weight_decay=weight_decay, mask=decay_mask,
                clip_norm=clip_norm, mu_dtype=mu_dtype)
    elif name == "adamw8bit":
        # int8 blockwise moments — 4x less optimizer HBM and update
        # bandwidth than f32 adamw (see optim8bit module doc); mu_dtype
        # is rejected above (the state is already 8-bit)
        from tensorflowonspark_tpu import optim8bit
        core = optim8bit.adamw8bit(sched, b1=b1 or 0.9, b2=b2 or 0.999,
                                   weight_decay=weight_decay,
                                   mask=decay_mask, layouts=layouts)
    elif name == "sgd":
        core = optax.sgd(sched, momentum=momentum)
    elif name == "lion":
        core = optax.lion(sched, b1=b1 or 0.9, b2=b2 or 0.99,
                          weight_decay=weight_decay, mask=decay_mask,
                          mu_dtype=mu_dtype)
    else:  # adafactor: the memory-frugal choice for big models
        core = optax.adafactor(sched)
    if clip_norm and name not in _FUSED:
        core = optax.chain(optax.clip_by_global_norm(clip_norm), core)
    logger.info("optimizer %s lr=%s schedule=%s warmup=%d wd=%s clip=%s",
                name, learning_rate, schedule, warmup_steps, weight_decay,
                clip_norm)
    return core, sched


def default_decay_mask(params):
    """True (decay) for >=2-D kernels, False for biases/norm scales."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: getattr(x, "ndim", 0) >= 2, params)
