"""Version shims for the JAX stack (maps reference compat.py:1-31).

The reference shimmed TF1/TF2 API drift; here we pin down the couple of JAX
API locations that have moved across releases so the rest of the codebase
imports from one place.
"""


def tree_map(f, *trees):
    import jax
    if hasattr(jax, "tree"):
        return jax.tree.map(f, *trees)
    return jax.tree_util.tree_map(f, *trees)


def shard_map():
    """Return the shard_map callable across jax versions, normalized to
    the current kwarg spelling: call sites pass ``check_vma``; on older
    jax (experimental entry point, ``check_rep``) a shim translates."""
    import inspect

    import jax
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    if "check_vma" in inspect.signature(sm).parameters:
        return sm

    def _compat(f, **kw):
        kw["check_rep"] = kw.pop("check_vma", True)
        return sm(f, **kw)
    return _compat


def make_mesh(axis_shapes, axis_names, devices=None):
    """Build a Mesh; prefers jax.make_mesh (better device ordering for ICI)."""
    import jax
    import numpy as np
    if devices is None and hasattr(jax, "make_mesh"):
        return jax.make_mesh(tuple(axis_shapes), tuple(axis_names))
    devs = np.asarray(devices if devices is not None else jax.devices())
    return jax.sharding.Mesh(devs.reshape(tuple(axis_shapes)), tuple(axis_names))


def export_chief_only(save_fn, is_chief, *args, **kwargs):
    """Run a model-export function on the chief only (reference: compat.py:10-17).

    The reference had non-chief workers save to a throwaway local dir because
    MultiWorkerMirroredStrategy required symmetric saves; JAX has no such
    requirement, so non-chief is a no-op.
    """
    if is_chief:
        return save_fn(*args, **kwargs)
    return None
