"""Executor substrate abstraction.

The reference is hard-wired to Spark: `TFCluster.run` does
`sc.parallelize(range(N), N).foreachPartition(...)` and feeders ride
`dataRDD.foreachPartition` (reference: TFCluster.py:297-334, :94).  This
framework factors that contract into a `Backend` interface with two
implementations:

- `SparkBackend` — thin wrappers over a live SparkContext (import-gated, since
  pyspark is optional).
- `LocalBackend`  — N real OS processes, one per "executor", each pinned to
  its own working directory.  This is both the test substrate (the TPU analog
  of the reference's 2-worker Spark standalone test cluster,
  tests/README.md:10) and a usable single-host runtime.

The contract every backend provides:
- `run_on_executors(fn, n)`  — launch the node-bootstrap closure once per
  executor, asynchronously; `fn` receives an iterator yielding the executor id.
- `foreach_partition(partitions, fn)` — run `fn(iter(partition))` for each
  partition, routed so partition i lands on executor i % n (feeders must land
  where a node's queue manager lives — the executor-id-file discovery trick,
  reference: util.py:77-94).
- `map_partitions(partitions, fn)` — same, collecting each call's result list.
"""
import logging
import multiprocessing as mp
import os
import queue as queue_mod
import tempfile
import threading
import time
import traceback

logger = logging.getLogger(__name__)


class Backend:
    """Interface; see module docstring."""

    @property
    def num_executors(self):
        raise NotImplementedError

    def run_on_executors(self, fn, n):
        raise NotImplementedError

    def foreach_partition(self, partitions, fn):
        raise NotImplementedError

    def map_partitions(self, partitions, fn):
        raise NotImplementedError


def _loads_fn(fn_or_blob):
    """Task fns cross the process boundary as cloudpickle blobs — the
    standard pickler spawn uses for Process args cannot serialize the
    nested closures cluster.run builds (node.run(...)'s _mapfn)."""
    if isinstance(fn_or_blob, bytes):
        import cloudpickle
        return cloudpickle.loads(fn_or_blob)
    return fn_or_blob


def _dumps_fn(fn):
    import cloudpickle
    return cloudpickle.dumps(fn)


def _task_trampoline(fn, part, result_q, index, workdir, collect):
    """Child-process shim: chdir to the executor dir, run, ship result/error."""
    try:
        fn = _loads_fn(fn)
        if workdir:
            os.chdir(workdir)
        out = fn(iter(part))
        if collect:
            result_q.put((index, "ok", list(out) if out is not None else []))
        else:
            # foreach: drain any generator for its side effects
            if out is not None:
                for _ in out:
                    pass
            result_q.put((index, "ok", None))
    except BaseException:
        result_q.put((index, "error", traceback.format_exc()))
        raise SystemExit(1)


def _bootstrap_trampoline(fn, executor_id, workdir, status_q, manager_linger=600):
    """Run a node bootstrap in its own process, then keep the executor alive
    while its node process and queue manager are needed — a stand-in for
    Spark's long-lived reused python-worker (reference precondition
    SPARK_REUSE_WORKER, TFSparkNode.py:393-395).

    Lifecycle: join the node process(es) first; then hold the queue manager
    open until the cluster-shutdown closure marks state 'stopped' (feeders
    and the shutdown path still need the queues after the node exits), with
    a linger timeout as a leak guard; then stop the manager and exit.
    """
    from tensorflowonspark_tpu import manager as manager_mod

    # SIGTERM (cluster.abort / LocalBackend.terminate) must take the
    # queue-manager SERVER down too: BaseManager.start forks it as a
    # separate child process, which would otherwise outlive this executor
    # as an orphan holding the manager port (and every inherited fd)
    import signal as signal_mod

    def _on_term(_signum, _frame):
        # children FIRST (the background node process — a grandchild
        # nothing else tracks; left alive it would keep training and
        # writing checkpoints into a relaunched attempt's resume), then
        # the manager server, then exit.  Async-signal-LEAN: raw os.kill
        # on snapshot-able pids only — no joins (active_children() reaps,
        # which can deadlock if SIGTERM lands while the main thread holds
        # the process lock) and no manager RPCs (shutdown() does a full
        # connection round trip)
        import multiprocessing.process as mp_process

        try:
            children = list(getattr(mp_process, "_children", ()))
        except Exception:
            children = []
        pids = [getattr(c, "pid", None) for c in children]
        for m in manager_mod._started_managers:
            pids.append(getattr(getattr(m, "_process", None), "pid", None))
        for pid in pids:
            if pid:
                try:
                    os.kill(pid, signal_mod.SIGTERM)
                except OSError:
                    pass
        os._exit(143)

    try:
        signal_mod.signal(signal_mod.SIGTERM, _on_term)
    except ValueError:
        pass    # not the main thread (never the case here)
    try:
        fn = _loads_fn(fn)
        os.chdir(workdir)
        fn(iter([executor_id]))
        status_q.put((executor_id, "ok", None))
        node_failed = False
        for child in mp.active_children():
            if child.name.startswith("QueueManager"):
                continue
            child.join()
            if child.exitcode not in (0, None):
                node_failed = True
                status_q.put((executor_id, "error",
                              f"node process {child.name} exited with "
                              f"code {child.exitcode}"))
        deadline = time.time() + manager_linger
        for mgr in manager_mod._started_managers:
            while time.time() < deadline:
                try:
                    state = manager_mod.get_value(mgr, "state")
                except Exception:
                    break  # server already gone
                if state == "stopped":
                    break
                time.sleep(0.5)
            try:
                mgr.shutdown()
            except Exception:
                pass
        if node_failed:
            raise SystemExit(1)
    except SystemExit:
        raise
    except BaseException:
        status_q.put((executor_id, "error", traceback.format_exc()))
        raise SystemExit(1)


class LocalBackend(Backend):
    """N-process local executor pool with per-executor working directories.

    Defaults to ``start_method="fork"`` — unlike minispark's ExecutorPool,
    which defaults to spawn.  This is the dev/CI backend: its tests fork
    dozens of short-lived executors from a JAX-loaded runner, and spawn
    would re-import jax (~10 s) in every one.  The fork-after-threads
    hazard is real; a long-lived multithreaded driver should pass
    ``start_method="spawn"`` (supported: task fns cross the process
    boundary as cloudpickle blobs, so closure fns survive spawn's
    standard pickler).
    """

    def __init__(self, num_executors, workdir=None, start_method="fork"):
        self._n = num_executors
        self._start_method = start_method
        self._ctx = mp.get_context(start_method)
        self._root = workdir or tempfile.mkdtemp(prefix="tfos-tpu-local-")
        self._dirs = []
        for i in range(num_executors):
            d = os.path.join(self._root, f"executor-{i}")
            os.makedirs(d, exist_ok=True)
            self._dirs.append(d)
        self._bootstrap_procs = []
        self._status_q = self._ctx.Queue()

    @property
    def num_executors(self):
        return self._n

    @property
    def executor_dirs(self):
        return list(self._dirs)

    def _ship_fn(self, fn):
        # fork ships Process args for free; only spawn needs the
        # cloudpickle blob (standard pickle rejects nested closures)
        return fn if self._start_method == "fork" else _dumps_fn(fn)

    def run_on_executors(self, fn, n):
        assert n == self._n, f"backend has {self._n} executors, asked for {n}"
        blob = self._ship_fn(fn)
        for i in range(n):
            p = self._ctx.Process(
                target=_bootstrap_trampoline,
                args=(blob, i, self._dirs[i], self._status_q),
                name=f"executor-{i}",
            )
            p.start()
            self._bootstrap_procs.append(p)

    def check_bootstrap_errors(self):
        """Non-blocking: return the first bootstrap error traceback, if any."""
        try:
            while True:
                _, kind, payload = self._status_q.get_nowait()
                if kind == "error":
                    return payload
        except queue_mod.Empty:
            return None

    def _run_tasks(self, partitions, fn, collect, timeout=None):
        """Run one task per partition: partitions for different executors run
        concurrently; multiple partitions routed to the SAME executor run
        sequentially.  Serialization per executor matters for correctness —
        Spark schedules one task per executor core (the reference's test
        cluster pins 1 core/executor, tox.ini:33-34), and concurrent feeders
        would interleave records on one queue, breaking the EndPartition
        1:1-result accounting."""
        parts = list(partitions)
        result_q = self._ctx.Queue()
        by_exec = {}
        for i, part in enumerate(parts):
            by_exec.setdefault(i % self._n, []).append((i, list(part)))

        live_procs = []
        cancelled = threading.Event()
        # terminate() reaps in-flight tasks AND stops the serial runners
        # from spawning the next queued one
        self._live_task_procs = live_procs
        self._tasks_cancelled = cancelled
        blob = self._ship_fn(fn)

        def _run_serial(eid, tasks):
            for index, part in tasks:
                if cancelled.is_set():
                    return
                p = self._ctx.Process(
                    target=_task_trampoline,
                    args=(blob, part, result_q, index, self._dirs[eid],
                          collect),
                    name=f"task-{index}",
                )
                p.start()
                live_procs.append(p)
                if cancelled.is_set():
                    # closes the cancel/start race: terminate() set the
                    # event and swept live_procs while we were between
                    # the loop check and p.start()
                    p.terminate()
                p.join()

        # daemon: the normal path joins these explicitly below, but an
        # abandoned run (cluster.abort mid-task, driver exception) must
        # not leave a non-daemon runner blocking interpreter shutdown
        threads = [threading.Thread(target=_run_serial, args=(eid, tasks),
                                    daemon=True)
                   for eid, tasks in by_exec.items()]
        for t in threads:
            t.start()
        results = [None] * len(parts)
        errors = []
        seen = 0
        deadline = None if timeout is None else time.time() + timeout
        while seen < len(parts):
            try:
                index, kind, payload = result_q.get(timeout=1)
            except queue_mod.Empty:
                if deadline is not None and time.time() > deadline:
                    # Bound the teardown: kill wedged task processes so the
                    # caller's timeout contract holds (the reference used
                    # SIGALRM on the driver, TFCluster.py:136-144).
                    cancelled.set()
                    for p in live_procs:
                        if p.is_alive():
                            p.terminate()
                    errors.append((-1, f"tasks exceeded {timeout}s timeout"))
                    break
                if not any(t.is_alive() for t in threads):
                    errors.append((-1, "task process died without reporting "
                                       "(killed or crashed hard)"))
                    break
                continue
            seen += 1
            if kind == "error":
                errors.append((index, payload))
            else:
                results[index] = payload
        for t in threads:
            t.join()
        if errors:
            errors.sort()
            index, tb = errors[0]
            raise RuntimeError(f"task {index} failed:\n{tb}")
        return results

    def foreach_partition(self, partitions, fn, timeout=None):
        self._run_tasks(partitions, fn, collect=False, timeout=timeout)

    def map_partitions(self, partitions, fn):
        nested = self._run_tasks(partitions, fn, collect=True)
        return [item for part in nested if part for item in part]

    def join(self, timeout=None):
        """Wait for all bootstrap (executor) processes to exit."""
        for p in self._bootstrap_procs:
            p.join(timeout)

    def terminate(self):
        # bootstraps AND in-flight task processes: a forceful teardown
        # (cluster.abort) must leave no children for multiprocessing's
        # atexit to join forever.  Cancel FIRST so the serial runner
        # threads don't spawn the next queued task after the kill.
        ev = getattr(self, "_tasks_cancelled", None)
        if ev is not None:
            ev.set()
        procs = list(self._bootstrap_procs) + list(
            getattr(self, "_live_task_procs", []))
        for p in procs:
            if p.is_alive():
                p.terminate()
        # SIGKILL escalation: a SIGTERM handler wedged on a lock (or a
        # process mid-fork) must not survive teardown
        deadline = time.time() + 5.0
        for p in procs:
            p.join(timeout=max(0.0, deadline - time.time()))
            if p.is_alive():
                p.kill()


class SparkBackend(Backend):
    """Backend over a live SparkContext (requires pyspark at call time).

    Maps the reference's direct Spark calls: node bootstrap via
    `sc.parallelize(range(n), n).foreachPartition` on a daemon thread
    (reference: TFCluster.py:297-334), feeding via RDD.foreachPartition, and
    inference via RDD.mapPartitions (reference: TFCluster.py:94,:115).
    """

    def __init__(self, sc):
        self._sc = sc

    @property
    def num_executors(self):
        return int(self._sc.defaultParallelism)

    @property
    def spark_context(self):
        return self._sc

    def run_on_executors(self, fn, n):
        import threading

        node_rdd = self._sc.parallelize(range(n), n)
        t = threading.Thread(target=node_rdd.foreachPartition, args=(fn,), daemon=True)
        t.start()

    @staticmethod
    def _adapt(fn):
        """Wrap a record-iterator closure for an RDD whose ELEMENTS are
        partition-lists (the shape `parallelize(list_of_partitions)`
        produces): unwrap one level so fn still sees records."""
        def run(element_iter):
            for part in element_iter:
                out = fn(iter(part))
                if out is not None:
                    yield from out
        return run

    def _as_rdd(self, partitions):
        """(rdd, fn_adapter) for either a real RDD or a list of partition
        lists.  Materializes generators exactly once so nothing is silently
        consumed before parallelize."""
        if hasattr(partitions, "foreachPartition"):
            return partitions, lambda fn: fn
        parts = [list(p) for p in partitions]
        return self._sc.parallelize(parts, max(len(parts), 1)), self._adapt

    def foreach_partition(self, partitions, fn, timeout=None):
        rdd, adapt = self._as_rdd(partitions)
        rdd.foreachPartition(adapt(fn))

    def map_partitions(self, partitions, fn):
        rdd, adapt = self._as_rdd(partitions)
        return rdd.mapPartitions(adapt(fn))  # lazy RDD, like the reference


def resolve(backend_or_sc):
    """Accept a Backend, or duck-typed SparkContext, and return a Backend."""
    if isinstance(backend_or_sc, Backend):
        return backend_or_sc
    if hasattr(backend_or_sc, "parallelize"):
        return SparkBackend(backend_or_sc)
    raise TypeError(f"cannot build an executor backend from {type(backend_or_sc)!r}")
