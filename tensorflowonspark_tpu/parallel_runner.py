"""Embarrassingly-parallel runner (maps reference TFParallel.py:17-64).

Runs N *independent* single-node instances of a user function — no
rendezvous, no collectives, no data feed — the shape the reference used for
parallel inference under Spark barrier mode.  Each instance gets a minimal
`NodeContext` (executor_id == task_index, every node is a "worker") and,
when several executors share a TPU host, a deterministic chip slice
(maps the BarrierTaskContext peer-placement GPU math, TFParallel.py:42-49).
"""
import logging

from . import backend as backend_mod
from . import node as node_mod
from . import tpu_info, util

logger = logging.getLogger(__name__)


def run(backend_or_sc, map_fn, tf_args=None, num_executors=None, num_chips=0):
    """Run `map_fn(tf_args, ctx)` once per executor, independently.

    Returns the collected per-node return values (a list; nodes returning
    None contribute nothing), where the reference returned nothing — the
    results channel is free on TPU because inference output need not ride a
    queue manager here.
    """
    backend = backend_mod.resolve(backend_or_sc)
    n = num_executors or backend.num_executors

    def _mapfn(iterator):
        executor_id = None
        for item in iterator:
            executor_id = item
        assert executor_id is not None, "parallel task received no executor id"
        if num_chips:
            tpu_info.assign_chips(num_chips,
                                  worker_index=_local_index(executor_id, num_chips))
        util.write_executor_id(executor_id)
        ctx = node_mod.NodeContext(
            executor_id=executor_id, job_name="worker",
            task_index=executor_id, num_workers=n)
        logger.info("parallel node %d/%d starting", executor_id, n)
        out = node_mod._wrapper_fn(map_fn, tf_args, ctx)
        return [] if out is None else [out]

    results = backend.map_partitions([[i] for i in range(n)], _mapfn)
    if hasattr(results, "collect"):
        # SparkBackend.map_partitions returns a lazy RDD; the reference's
        # barrier-mode run executed eagerly (TFParallel.py:63-64), and
        # callers ported from it discard the return value — force the jobs.
        results = results.collect()
    return results


def _local_index(executor_id, num_chips):
    """Host-local worker index for chip slicing.

    Under Spark barrier mode the task infos give exact same-host peer ranks
    (what the reference used, TFParallel.py:42-49); otherwise fall back to
    executor_id modulo the host's worker-slot count (local chips / chips per
    worker) — exact for LocalBackend (single host) and for contiguous-block
    executor placement.
    """
    try:
        from pyspark import BarrierTaskContext
        tc = BarrierTaskContext.get()
        infos = tc.getTaskInfos()
        host = util.get_ip_address()
        peers = [i for i, ti in enumerate(infos) if ti.address.split(":")[0]
                 in (host, "localhost", "127.0.0.1")]
        return peers.index(tc.partitionId())
    except Exception:
        slots = max(tpu_info._count_local_chips() // max(num_chips, 1), 1)
        return executor_id % slots
