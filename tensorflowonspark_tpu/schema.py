"""Schema-string parsing for the data-interchange layer.

Maps the reference's Scala parser-combinator SimpleTypeParser
(reference: src/main/scala/com/yahoo/tensorflowonspark/SimpleTypeParser.scala:27-63),
which parses Spark's ``StructType.simpleString`` format:

    struct<name:type,...>   with base types binary/boolean/int/long/bigint/
    float/double/string and 1-D arrays array<base>.

Used by the inference CLI (--schema_hint) and dfutil.loadTFRecords to
disambiguate TFRecord feature decoding (e.g. bytes vs string, float vs
double) the same way the reference's schemaHint does
(reference: DFUtil.scala:35-110).
"""

BASE_TYPES = {
    "binary": "binary",
    "boolean": "bool",
    "int": "int32",
    "long": "int64",
    "bigint": "int64",
    "float": "float32",
    "double": "float64",
    "string": "string",
}


class Field:
    """One parsed column: name, numpy-ish dtype name, is_array flag."""

    def __init__(self, name, dtype, is_array=False):
        self.name = name
        self.dtype = dtype
        self.is_array = is_array

    def __repr__(self):
        inner = f"array<{self.dtype}>" if self.is_array else self.dtype
        return f"Field({self.name}:{inner})"

    def __eq__(self, other):
        return (isinstance(other, Field) and self.name == other.name
                and self.dtype == other.dtype and self.is_array == other.is_array)


def parse_struct(s):
    """``struct<a:int,b:array<float>>`` -> [Field...] (order preserved)."""
    s = s.strip()
    if not (s.startswith("struct<") and s.endswith(">")):
        raise ValueError(f"schema must look like struct<name:type,...>: {s!r}")
    body = s[len("struct<"):-1].strip()
    fields = []
    if not body:
        return fields
    # split on commas not inside array<...>
    parts, depth, cur = [], 0, []
    for ch in body:
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))

    for part in parts:
        name, sep, typ = part.partition(":")
        name, typ = name.strip(), typ.strip().lower()
        if not sep or not name or not typ:
            raise ValueError(f"bad field {part!r} (want name:type)")
        if typ.startswith("array<") and typ.endswith(">"):
            base = typ[len("array<"):-1].strip()
            if base not in BASE_TYPES:
                raise ValueError(f"unsupported array element type {base!r}")
            fields.append(Field(name, BASE_TYPES[base], is_array=True))
        elif typ in BASE_TYPES:
            fields.append(Field(name, BASE_TYPES[typ]))
        else:
            raise ValueError(
                f"unsupported type {typ!r}; supported: "
                f"{sorted(BASE_TYPES)} and array<> of those")
    return fields


def to_simple_string(fields):
    """[Field...] -> ``struct<...>`` round trip."""
    inv = {v: k for k, v in BASE_TYPES.items() if k != "bigint"}
    cols = ",".join(
        f"{f.name}:array<{inv[f.dtype]}>" if f.is_array else f"{f.name}:{inv[f.dtype]}"
        for f in fields)
    return f"struct<{cols}>"
