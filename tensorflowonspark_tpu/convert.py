"""Checkpoint interoperability: HuggingFace -> framework weight conversion.

The reference's users bring existing TF models; this framework's users
bring existing PyTorch/HuggingFace checkpoints.  `from_hf_gpt2` maps a
``transformers.GPT2LMHeadModel`` (instance or pretrained path) onto the
flagship `models.transformer.Transformer` — architecturally identical
(pre-LN blocks, learned positions, tanh-approx GELU, tied lm_head) once
``use_bias=True`` — so generation/serving/fine-tuning run TPU-native with
the framework's sharding rules applied to the imported weights.

Numerical parity is exact (float32): see tests/test_convert.py, which
checks logits against the torch forward pass on a random GPT-2.

Offline-friendly: accepts an in-memory model or a local directory;
nothing is fetched.
"""
import logging

import numpy as np

logger = logging.getLogger(__name__)


def _t(tensor):
    return np.asarray(tensor.detach().cpu().numpy())


def gpt2_config(hf_cfg, **overrides):
    """TransformerConfig matching a ``transformers.GPT2Config``."""
    from .models.transformer import TransformerConfig

    # refuse configs whose attention numerics would silently diverge;
    # activations map onto the configurable MLP activation
    act = getattr(hf_cfg, "activation_function", "gelu_new")
    act_map = {"gelu_new": "gelu_tanh", "gelu_pytorch_tanh": "gelu_tanh",
               "gelu": "gelu_exact", "relu": "relu", "silu": "silu"}
    if act not in act_map:
        raise ValueError(f"unsupported activation_function={act!r}")
    for flag, bad in (("scale_attn_weights", False),
                      ("scale_attn_by_inverse_layer_idx", True),
                      ("reorder_and_upcast_attn", True)):
        if getattr(hf_cfg, flag, not bad) == bad:
            raise ValueError(f"unsupported GPT2Config {flag}={bad} "
                             "(attention numerics would diverge)")
    kw = dict(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.n_embd,
        n_heads=hf_cfg.n_head,
        n_kv_heads=None,                     # GPT-2 is MHA
        n_layers=hf_cfg.n_layer,
        d_ff=(hf_cfg.n_inner if hf_cfg.n_inner is not None
              else 4 * hf_cfg.n_embd),
        max_seq_len=hf_cfg.n_positions,
        causal=True,
        rope=False,                          # learned absolute positions
        use_bias=True,
        ln_eps=hf_cfg.layer_norm_epsilon,
        activation=act_map[act],
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def _finalize(params, label, n_layers):
    """float32 master copies + a conversion log line."""
    import jax
    import jax.numpy as jnp

    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32), params)
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    logger.info("converted %s (%d layers, %.1fM params)", label, n_layers,
                n / 1e6)
    return params


def _linear(sd, key):
    """HF torch.nn.Linear stores weight [out, in]; flax Dense kernel is
    [in, out]."""
    return {"kernel": _t(sd[key + ".weight"]).T,
            "bias": _t(sd[key + ".bias"])}


def bert_config(hf_cfg, **overrides):
    """models.bert.BertConfig matching a ``transformers.BertConfig``."""
    from .models.bert import BertConfig

    act = getattr(hf_cfg, "hidden_act", "gelu")
    act_map = {"gelu": "gelu_exact", "gelu_new": "gelu_tanh",
               "gelu_pytorch_tanh": "gelu_tanh", "relu": "relu"}
    if act not in act_map:
        raise ValueError(f"unsupported hidden_act={act!r}")
    if getattr(hf_cfg, "position_embedding_type", "absolute") != "absolute":
        raise ValueError("only absolute position embeddings are supported")
    if getattr(hf_cfg, "is_decoder", False) or getattr(
            hf_cfg, "add_cross_attention", False):
        raise ValueError("decoder-style BERT (is_decoder/add_cross_attention)"
                         " is not supported: models.bert is a bidirectional"
                         " encoder with no cross-attention")
    kw = dict(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_heads=hf_cfg.num_attention_heads,
        n_layers=hf_cfg.num_hidden_layers,
        d_ff=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        type_vocab_size=hf_cfg.type_vocab_size,
        ln_eps=hf_cfg.layer_norm_eps,
        activation=act_map[act],
    )
    kw.update(overrides)
    return BertConfig(**kw)


def from_hf_bert(model_or_path, dtype="float32", **config_overrides):
    """Convert an HF BERT to (BertConfig, params).

    Accepts ``BertModel`` or ``BertForPreTraining`` (instance or local
    path).  Returns encoder params under the layout `models.bert.
    BertEncoder` expects; with a BertForPreTraining input the MLM/NSP
    head weights (`mlm_dense`, `mlm_ln`, `mlm_bias`, `pooler`,
    `nsp_head`) are included for `models.bert.BertForPreTraining` (whose
    encoder lives under the "encoder" scope).
    """
    if isinstance(model_or_path, str):
        from transformers import AutoConfig, AutoModel, AutoModelForPreTraining
        archs = getattr(AutoConfig.from_pretrained(model_or_path),
                        "architectures", None) or []
        loader = (AutoModelForPreTraining if "BertForPreTraining" in archs
                  else AutoModel)
        model = loader.from_pretrained(model_or_path)
    else:
        model = model_or_path
    sd = model.state_dict()
    cfg = bert_config(model.config, dtype=dtype, **config_overrides)
    # BertModel keys have no prefix; BertForPreTraining prefixes "bert."
    kind = type(model).__name__
    if "embeddings.word_embeddings.weight" in sd:
        pre = ""
    elif ("bert.embeddings.word_embeddings.weight" in sd
          and "cls.seq_relationship.weight" in sd
          and "cls.predictions.transform.dense.weight" in sd):
        pre = "bert."
    else:
        raise ValueError(
            f"unsupported model class {kind}: pass a BertModel (encoder) "
            "or BertForPreTraining (encoder + MLM/NSP heads)")
    dec = sd.get(pre and "cls.predictions.decoder.weight")
    if dec is not None:
        import torch as _torch
        if not _torch.equal(dec, sd[pre + "embeddings.word_embeddings.weight"]):
            raise ValueError(
                "untied MLM decoder (tie_word_embeddings=False) is not "
                "supported: models.bert ties MLM logits to the embedding")

    enc = {
        "token_embed": {"embedding":
                        _t(sd[pre + "embeddings.word_embeddings.weight"])},
        "pos_embed": {"embedding":
                      _t(sd[pre + "embeddings.position_embeddings.weight"])},
        "type_embed": {"embedding":
                       _t(sd[pre + "embeddings.token_type_embeddings.weight"])},
        "ln_embed": {"scale": _t(sd[pre + "embeddings.LayerNorm.weight"]),
                     "bias": _t(sd[pre + "embeddings.LayerNorm.bias"])},
    }
    for i in range(cfg.n_layers):
        lp = f"{pre}encoder.layer.{i}."
        enc[f"layer_{i}"] = {
            "attn": {
                "query": _linear(sd, lp + "attention.self.query"),
                "key": _linear(sd, lp + "attention.self.key"),
                "value": _linear(sd, lp + "attention.self.value"),
                "out": _linear(sd, lp + "attention.output.dense"),
            },
            # post-LN: ln1 follows attention, ln2 follows the MLP
            "ln1": {"scale": _t(sd[lp + "attention.output.LayerNorm.weight"]),
                    "bias": _t(sd[lp + "attention.output.LayerNorm.bias"])},
            "mlp": {
                "wi": _linear(sd, lp + "intermediate.dense"),
                "wo": _linear(sd, lp + "output.dense"),
            },
            "ln2": {"scale": _t(sd[lp + "output.LayerNorm.weight"]),
                    "bias": _t(sd[lp + "output.LayerNorm.bias"])},
        }
    params = enc
    if pre:  # BertForPreTraining: heads + pooler around the encoder scope
        params = {"encoder": enc}
        params["mlm_dense"] = _linear(sd, "cls.predictions.transform.dense")
        params["mlm_ln"] = {
            "scale": _t(sd["cls.predictions.transform.LayerNorm.weight"]),
            "bias": _t(sd["cls.predictions.transform.LayerNorm.bias"])}
        params["mlm_bias"] = _t(sd["cls.predictions.bias"])
        params["pooler"] = _linear(sd, "bert.pooler.dense")
        params["nsp_head"] = _linear(sd, "cls.seq_relationship")
    return cfg, _finalize(params, f"BERT[{kind}]", cfg.n_layers)


def from_hf_gpt2(model_or_path, dtype="float32", **config_overrides):
    """Convert a GPT-2 LM to (TransformerConfig, params).

    `model_or_path`: a ``GPT2LMHeadModel`` instance or a local directory
    for ``GPT2LMHeadModel.from_pretrained``.  Extra kwargs override config
    fields (e.g. ``attention_impl="flash"``, ``dtype="bfloat16"``).
    """
    if isinstance(model_or_path, str):
        from transformers import GPT2LMHeadModel
        model = GPT2LMHeadModel.from_pretrained(model_or_path)
    else:
        model = model_or_path
    sd = model.state_dict()
    hf_cfg = model.config
    cfg = gpt2_config(hf_cfg, dtype=dtype, **config_overrides)

    params = {
        "token_embed": {"embedding": _t(sd["transformer.wte.weight"])},
        "pos_embed": {"embedding": _t(sd["transformer.wpe.weight"])},
        "ln_f": {"scale": _t(sd["transformer.ln_f.weight"]),
                 "bias": _t(sd["transformer.ln_f.bias"])},
        # lm_head.weight aliases wte when tied (the GPT-2 default) and is
        # the real output projection when untied — use it either way
        "lm_head": {"kernel": _t(sd["lm_head.weight"]).T},
    }
    for i in range(cfg.n_layers):
        pre = f"transformer.h.{i}."
        # HF Conv1D stores weights [in, out] — flax Dense kernel layout
        w_attn = _t(sd[pre + "attn.c_attn.weight"])      # [d, 3d]
        b_attn = _t(sd[pre + "attn.c_attn.bias"])        # [3d]
        wq, wk, wv = np.split(w_attn, 3, axis=1)
        bq, bk, bv = np.split(b_attn, 3)
        params[f"layer_{i}"] = {
            "ln1": {"scale": _t(sd[pre + "ln_1.weight"]),
                    "bias": _t(sd[pre + "ln_1.bias"])},
            "ln2": {"scale": _t(sd[pre + "ln_2.weight"]),
                    "bias": _t(sd[pre + "ln_2.bias"])},
            "attn": {
                "query": {"kernel": wq, "bias": bq},
                "key": {"kernel": wk, "bias": bk},
                "value": {"kernel": wv, "bias": bv},
                "out": {"kernel": _t(sd[pre + "attn.c_proj.weight"]),
                        "bias": _t(sd[pre + "attn.c_proj.bias"])},
            },
            "mlp": {
                "wi": {"kernel": _t(sd[pre + "mlp.c_fc.weight"]),
                       "bias": _t(sd[pre + "mlp.c_fc.bias"])},
                "wo": {"kernel": _t(sd[pre + "mlp.c_proj.weight"]),
                       "bias": _t(sd[pre + "mlp.c_proj.bias"])},
            },
        }
    # params are float32 master copies regardless of the compute dtype;
    # cfg.dtype controls activation precision inside the model
    return cfg, _finalize(params, "GPT-2", cfg.n_layers)


def llama_config(hf_cfg, **overrides):
    """TransformerConfig matching a ``transformers.LlamaConfig`` (the
    LLaMA / Mistral-style decoder family: RMSNorm, RoPE, GQA, SwiGLU)."""
    from .models.transformer import TransformerConfig

    act = getattr(hf_cfg, "hidden_act", "silu")
    act_map = {"silu": "silu", "gelu": "gelu_exact",
               "gelu_pytorch_tanh": "gelu_tanh"}
    if act not in act_map:
        raise ValueError(f"unsupported hidden_act={act!r}")
    if getattr(hf_cfg, "rope_scaling", None):
        raise ValueError("rope_scaling is not supported (plain RoPE only)")
    if getattr(hf_cfg, "attention_dropout", 0.0):
        raise ValueError("attention_dropout != 0 is not supported")
    head_dim = getattr(hf_cfg, "head_dim", None)
    if head_dim and head_dim * hf_cfg.num_attention_heads != \
            hf_cfg.hidden_size:
        raise ValueError(f"head_dim={head_dim} * num_attention_heads != "
                         "hidden_size (non-standard head widths would "
                         "change the q/k/v projection shapes)")
    if getattr(hf_cfg, "attention_bias", False):
        # TransformerConfig.use_bias covers attention AND MLP denses;
        # attention-only bias (Qwen-style) is not expressible
        raise ValueError("attention_bias=True is not supported")
    if getattr(hf_cfg, "mlp_bias", False):
        # silently dropping the bias tensors would convert to wrong logits
        raise ValueError("mlp_bias=True is not supported")
    kw = dict(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=getattr(hf_cfg, "num_key_value_heads",
                           hf_cfg.num_attention_heads),
        n_layers=hf_cfg.num_hidden_layers,
        d_ff=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        causal=True,
        rope=True,
        rope_theta=float(getattr(hf_cfg, "rope_theta", 10000.0)),
        use_bias=False,
        ln_eps=hf_cfg.rms_norm_eps,
        norm_type="rmsnorm",
        mlp_style="gated",
        activation=act_map[act],
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def from_hf_llama(model_or_path, dtype="float32", **config_overrides):
    """Convert a LLaMA-family causal LM to (TransformerConfig, params).

    `model_or_path`: a ``LlamaForCausalLM`` instance or a local directory
    for ``LlamaForCausalLM.from_pretrained``.  The architecture maps 1:1
    onto the flagship Transformer: RMSNorm -> norm_type='rmsnorm', SwiGLU
    -> mlp_style='gated', GQA -> n_kv_heads, rotate-half RoPE ->
    apply_rope (identical split-half convention).  Numerical parity is
    checked against the torch forward pass in tests/test_convert.py.
    """
    if isinstance(model_or_path, str):
        from transformers import LlamaForCausalLM
        model = LlamaForCausalLM.from_pretrained(model_or_path)
    else:
        model = model_or_path
    sd = model.state_dict()
    hf_cfg = model.config
    cfg = llama_config(hf_cfg, dtype=dtype, **config_overrides)

    # tied embeddings (tie_word_embeddings=True) omit lm_head.weight from
    # the state dict — the unembedding IS the token table either way
    lm_w = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
    params = {
        "token_embed": {"embedding": _t(sd["model.embed_tokens.weight"])},
        "ln_f": {"scale": _t(sd["model.norm.weight"])},
        "lm_head": {"kernel": _t(lm_w).T},
    }
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."

        def proj(name, pre=pre):
            return {"kernel": _t(sd[pre + f"self_attn.{name}.weight"]).T}

        params[f"layer_{i}"] = {
            "ln1": {"scale": _t(sd[pre + "input_layernorm.weight"])},
            "ln2": {"scale": _t(
                sd[pre + "post_attention_layernorm.weight"])},
            "attn": {
                "query": proj("q_proj"),
                "key": proj("k_proj"),
                "value": proj("v_proj"),
                "out": proj("o_proj"),
            },
            "mlp": {
                "wi_gate": {"kernel": _t(
                    sd[pre + "mlp.gate_proj.weight"]).T},
                "wi_up": {"kernel": _t(sd[pre + "mlp.up_proj.weight"]).T},
                "wo": {"kernel": _t(sd[pre + "mlp.down_proj.weight"]).T},
            },
        }
    return cfg, _finalize(params, "LLaMA", cfg.n_layers)


def mixtral_config(hf_cfg, **overrides):
    """TransformerConfig matching a ``transformers.MixtralConfig``
    (LLaMA-style attention + a gated-expert MoE MLP in EVERY layer).

    Router parity: Mixtral softmaxes the router logits, picks top-k, and
    renormalizes over the chosen experts — exactly this framework's
    ``moe_router='topk'`` convention (softmax is monotonic, so top-k over
    probabilities equals top-k over logits).  Mixtral drops no tokens, so
    the default capacity factor here is E/k (capacity == every token);
    lower it explicitly to fine-tune with GShard capacity bounds.
    """
    E = hf_cfg.num_local_experts
    k = hf_cfg.num_experts_per_tok
    base = llama_config(hf_cfg)
    # sliding-window attention is not implemented; HF only applies the
    # window beyond `sliding_window` tokens, so sequences at or under it
    # are numerics-identical — clamp max_seq_len to stay in that regime
    window = getattr(hf_cfg, "sliding_window", None)
    max_seq = base.max_seq_len if window is None \
        else min(base.max_seq_len, int(window))
    kw = dict(
        base.__dict__,
        max_seq_len=max_seq,
        num_experts=E,
        moe_every=1,
        moe_router="topk",
        moe_top_k=k,
        moe_capacity_factor=float(E) / float(k),
    )
    kw.update(overrides)
    from .models.transformer import TransformerConfig
    return TransformerConfig(**kw)


def from_hf_mixtral(model_or_path, dtype="float32", **config_overrides):
    """Convert a Mixtral MoE causal LM to (TransformerConfig, params).

    `model_or_path`: a ``MixtralForCausalLM`` instance or a local
    directory.  Attention/norm weights map like LLaMA; each layer's
    block-sparse MoE maps onto MoEMLP's stacked expert tensors
    (w1 -> experts_wi gate, w3 -> experts_up, w2 -> experts_wo, the
    router gate -> router/kernel).  Logit parity vs the torch forward
    pass is checked in tests/test_convert.py.
    """
    if isinstance(model_or_path, str):
        from transformers import MixtralForCausalLM
        model = MixtralForCausalLM.from_pretrained(model_or_path)
    else:
        model = model_or_path
    sd = model.state_dict()
    hf_cfg = model.config
    cfg = mixtral_config(hf_cfg, dtype=dtype, **config_overrides)
    E = cfg.num_experts

    lm_w = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
    params = {
        "token_embed": {"embedding": _t(sd["model.embed_tokens.weight"])},
        "ln_f": {"scale": _t(sd["model.norm.weight"])},
        "lm_head": {"kernel": _t(lm_w).T},
    }
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."
        moe = pre + "block_sparse_moe."

        def proj(name, pre=pre):
            return {"kernel": _t(sd[pre + f"self_attn.{name}.weight"]).T}

        def experts(w, moe=moe):
            # HF Linear [out, in] -> stacked [E, in, out]
            return np.stack([_t(sd[moe + f"experts.{e}.{w}.weight"]).T
                             for e in range(E)])

        params[f"layer_{i}"] = {
            "ln1": {"scale": _t(sd[pre + "input_layernorm.weight"])},
            "ln2": {"scale": _t(
                sd[pre + "post_attention_layernorm.weight"])},
            "attn": {
                "query": proj("q_proj"),
                "key": proj("k_proj"),
                "value": proj("v_proj"),
                "out": proj("o_proj"),
            },
            "moe": {
                "router": {"kernel": _t(sd[moe + "gate.weight"]).T},
                "experts_wi/kernel": experts("w1"),
                "experts_up/kernel": experts("w3"),
                "experts_wo/kernel": experts("w2"),
            },
        }
    return cfg, _finalize(params, "Mixtral", cfg.n_layers)
