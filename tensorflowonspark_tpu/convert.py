"""Checkpoint interoperability: HuggingFace -> framework weight conversion.

The reference's users bring existing TF models; this framework's users
bring existing PyTorch/HuggingFace checkpoints.  `from_hf_gpt2` maps a
``transformers.GPT2LMHeadModel`` (instance or pretrained path) onto the
flagship `models.transformer.Transformer` — architecturally identical
(pre-LN blocks, learned positions, tanh-approx GELU, tied lm_head) once
``use_bias=True`` — so generation/serving/fine-tuning run TPU-native with
the framework's sharding rules applied to the imported weights.

Numerical parity is exact (float32): see tests/test_convert.py, which
checks logits against the torch forward pass on a random GPT-2.

Offline-friendly: accepts an in-memory model or a local directory;
nothing is fetched.
"""
import logging

import numpy as np

logger = logging.getLogger(__name__)


def _t(tensor):
    return np.asarray(tensor.detach().cpu().numpy())


def gpt2_config(hf_cfg, **overrides):
    """TransformerConfig matching a ``transformers.GPT2Config``."""
    from .models.transformer import TransformerConfig

    # the flax model hardcodes tanh-GELU and 1/sqrt(head_dim) attention
    # scaling; refuse configs whose numerics would silently diverge
    act = getattr(hf_cfg, "activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"unsupported activation_function={act!r} "
                         "(the model uses tanh-approximate GELU)")
    for flag, bad in (("scale_attn_weights", False),
                      ("scale_attn_by_inverse_layer_idx", True),
                      ("reorder_and_upcast_attn", True)):
        if getattr(hf_cfg, flag, not bad) == bad:
            raise ValueError(f"unsupported GPT2Config {flag}={bad} "
                             "(attention numerics would diverge)")
    kw = dict(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.n_embd,
        n_heads=hf_cfg.n_head,
        n_kv_heads=None,                     # GPT-2 is MHA
        n_layers=hf_cfg.n_layer,
        d_ff=(hf_cfg.n_inner if hf_cfg.n_inner is not None
              else 4 * hf_cfg.n_embd),
        max_seq_len=hf_cfg.n_positions,
        causal=True,
        rope=False,                          # learned absolute positions
        use_bias=True,
        ln_eps=hf_cfg.layer_norm_epsilon,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def from_hf_gpt2(model_or_path, dtype="float32", **config_overrides):
    """Convert a GPT-2 LM to (TransformerConfig, params).

    `model_or_path`: a ``GPT2LMHeadModel`` instance or a local directory
    for ``GPT2LMHeadModel.from_pretrained``.  Extra kwargs override config
    fields (e.g. ``attention_impl="flash"``, ``dtype="bfloat16"``).
    """
    if isinstance(model_or_path, str):
        from transformers import GPT2LMHeadModel
        model = GPT2LMHeadModel.from_pretrained(model_or_path)
    else:
        model = model_or_path
    sd = model.state_dict()
    hf_cfg = model.config
    cfg = gpt2_config(hf_cfg, dtype=dtype, **config_overrides)

    params = {
        "token_embed": {"embedding": _t(sd["transformer.wte.weight"])},
        "pos_embed": {"embedding": _t(sd["transformer.wpe.weight"])},
        "ln_f": {"scale": _t(sd["transformer.ln_f.weight"]),
                 "bias": _t(sd["transformer.ln_f.bias"])},
        # lm_head.weight aliases wte when tied (the GPT-2 default) and is
        # the real output projection when untied — use it either way
        "lm_head": {"kernel": _t(sd["lm_head.weight"]).T},
    }
    for i in range(cfg.n_layers):
        pre = f"transformer.h.{i}."
        # HF Conv1D stores weights [in, out] — flax Dense kernel layout
        w_attn = _t(sd[pre + "attn.c_attn.weight"])      # [d, 3d]
        b_attn = _t(sd[pre + "attn.c_attn.bias"])        # [3d]
        wq, wk, wv = np.split(w_attn, 3, axis=1)
        bq, bk, bv = np.split(b_attn, 3)
        params[f"layer_{i}"] = {
            "ln1": {"scale": _t(sd[pre + "ln_1.weight"]),
                    "bias": _t(sd[pre + "ln_1.bias"])},
            "ln2": {"scale": _t(sd[pre + "ln_2.weight"]),
                    "bias": _t(sd[pre + "ln_2.bias"])},
            "attn": {
                "query": {"kernel": wq, "bias": bq},
                "key": {"kernel": wk, "bias": bk},
                "value": {"kernel": wv, "bias": bv},
                "out": {"kernel": _t(sd[pre + "attn.c_proj.weight"]),
                        "bias": _t(sd[pre + "attn.c_proj.bias"])},
            },
            "mlp": {
                "wi": {"kernel": _t(sd[pre + "mlp.c_fc.weight"]),
                       "bias": _t(sd[pre + "mlp.c_fc.bias"])},
                "wo": {"kernel": _t(sd[pre + "mlp.c_proj.weight"]),
                       "bias": _t(sd[pre + "mlp.c_proj.bias"])},
            },
        }
    import jax
    import jax.numpy as jnp

    # params are float32 master copies regardless of the compute dtype;
    # cfg.dtype controls activation precision inside the model
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32), params)
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    logger.info("converted GPT-2 (%d layers, %.1fM params)", cfg.n_layers,
                n / 1e6)
    return cfg, params
