"""Saved-model export/load — the TPU-native SavedModel analog.

The reference exports TF SavedModels (TFNode.export_saved_model,
reference: TFNode.py:159-208; chief-only gating in compat.py:10-17) and its
pipeline/JVM layers reload them by signature (pipeline.py:585-644,
TFModel.scala:245-292).  Here the export artifact is a directory holding:

- ``tfos_model.json`` — a *builder spec* (``"module:callable"`` import path
  + JSON kwargs) that reconstructs the model, plus named **signatures**
  describing input tensor names/shapes/dtypes and output names.  Shapes are
  recorded because tabular sources (Spark Rows) carry flat arrays that must
  be coerced back to tensor shapes at serving time (the reference does the
  same dance at pipeline.py:615-644).
- ``params.msgpack`` — the parameter pytree (flax serialization).

``load_saved_model`` rebuilds ``(apply_fn, params, signature)`` — the serving
triple that pipeline.TFModel and the native batch-inference runner consume.
"""
import importlib
import json
import logging

logger = logging.getLogger(__name__)

MODEL_SPEC = "tfos_model.json"
PARAMS_FILE = "params.msgpack"
DEFAULT_SIGNATURE = "serving_default"  # reference: pipeline.py:276 default


def _resolve_builder(spec):
    """Import ``"module:callable"`` → the callable."""
    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(f"builder spec {spec!r} must look like 'module:callable'")
    obj = importlib.import_module(mod_name)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def export_saved_model(export_dir, params, builder, builder_kwargs=None,
                       signatures=None, is_chief=True, aot_batch_sizes=None,
                       aot_platforms=None, quantize_int8=False,
                       quantize_kwargs=None):
    """Write the serving artifact (maps TFNode.export_saved_model).

    - ``builder``: ``"module:callable"`` import path.  Called with
      ``**builder_kwargs`` it must return either a flax ``nn.Module`` (its
      ``.apply`` is used) or a plain ``apply(params, *inputs)`` callable.
    - ``signatures``: {name: {"inputs": {in_name: {"shape": [...],
      "dtype": "float32"}}, "outputs": [out_names]}}; defaults to a single
      ``serving_default`` with one unconstrained input.
    - Non-chief processes no-op, like the reference's chief-only export.
    - ``aot_batch_sizes``: additionally AOT-compile the default signature to
      StableHLO at these serving batch sizes (aot.export_aot) so the C++
      PJRT runner / CLI can serve the model with no Python model code.
    - ``quantize_int8``: store kernels as per-channel int8
      (quantize.quantize_tree; ``quantize_kwargs`` forwards
      targets/min_elements/axis) — ~4x smaller artifact and weight HBM
      traffic; `load_saved_model` transparently dequantizes inside the
      apply fn (fused into the matmuls under jit, in the model's
      serving dtype), and an AOT artifact bakes the int8 weights +
      dequant into the StableHLO.
    """
    if not is_chief:
        logger.info("non-chief process skipping export to %s", export_dir)
        return None
    _resolve_builder(builder)  # fail fast on a bad spec
    import flax.serialization

    dequant_dtype = None
    if quantize_int8:
        import jax
        import jax.numpy as jnp

        from . import quantize as quantize_mod
        dtypes = {str(x.dtype) for x in jax.tree_util.tree_leaves(params)
                  if jnp.issubdtype(getattr(x, "dtype", jnp.int32),
                                    jnp.floating)}
        # remember the narrowest float dtype so serving dequantizes back
        # into the model's compute precision (W8A16), not f32
        dequant_dtype = ("bfloat16" if "bfloat16" in dtypes
                         else ("float16" if "float16" in dtypes
                               else "float32"))
        params = quantize_mod.quantize_tree(params,
                                            **(quantize_kwargs or {}))
    from . import fsio
    if aot_batch_sizes and fsio.is_remote(export_dir):
        # checked BEFORE any write so a multi-GB params upload is not
        # wasted on an export that cannot finish
        raise ValueError(
            "aot_batch_sizes requires a local export_dir: AOT artifacts "
            "(compiled executables / native runner inputs) must be local "
            "files — export locally, then copy the directory")
    fsio.makedirs(export_dir)
    spec = {
        "format": "tfos-tpu-saved-model",
        "version": 1,
        "builder": builder,
        "builder_kwargs": builder_kwargs or {},
        "signatures": signatures or {
            DEFAULT_SIGNATURE: {"inputs": {"input": {}}, "outputs": ["output"]}},
    }
    if quantize_int8:
        spec["quantized"] = "int8"
        spec["dequant_dtype"] = dequant_dtype
    with fsio.fopen(fsio.join(export_dir, MODEL_SPEC), "w") as f:
        json.dump(spec, f, indent=2)
    with fsio.fopen(fsio.join(export_dir, PARAMS_FILE), "wb") as f:
        f.write(flax.serialization.to_bytes(params))
    logger.info("exported saved model to %s", export_dir)

    if aot_batch_sizes:
        from . import aot as aot_mod

        # AOT-compile the default signature when present, else the sole /
        # first declared one (callers may use custom signature names)
        sig_names = list(spec["signatures"])
        sig_key = (DEFAULT_SIGNATURE if DEFAULT_SIGNATURE in sig_names
                   else sig_names[0])
        apply_fn, loaded_params, signature = load_saved_model(
            export_dir, signature_def_key=sig_key)
        aot_mod.export_aot(export_dir, apply_fn, loaded_params, signature,
                           batch_sizes=aot_batch_sizes,
                           platforms=aot_platforms)
    return export_dir


def _read_spec(export_dir):
    """Read + format-check ``tfos_model.json``."""
    from . import fsio
    with fsio.fopen(fsio.join(export_dir, MODEL_SPEC), "r") as f:
        spec = json.load(f)
    if spec.get("format") != "tfos-tpu-saved-model":
        raise ValueError(f"{export_dir} is not a tfos-tpu saved model")
    return spec


def read_signature(export_dir, signature_def_key=None):
    """Read ``(spec, signature)`` from an export dir without loading
    params — the cheap metadata half of `load_saved_model` (format check
    and signature lookup included)."""
    spec = _read_spec(export_dir)
    sig_key = signature_def_key or DEFAULT_SIGNATURE
    try:
        return spec, spec["signatures"][sig_key]
    except KeyError:
        raise ValueError(
            f"signature {sig_key!r} not found; available: "
            f"{sorted(spec['signatures'])}") from None


def _restore_params(export_dir):
    """Deserialize the params tree from an export dir (msgpack; unwraps a
    sole {'params': ...} envelope).  Quantized trees come back AS STORED —
    dequantization policy belongs to the caller."""
    from . import fsio
    import flax.serialization

    with fsio.fopen(fsio.join(export_dir, PARAMS_FILE), "rb") as f:
        params = flax.serialization.msgpack_restore(f.read())
    if isinstance(params, dict) and set(params) == {"params"}:
        params = params["params"]
    return params


def load_model(export_dir, dequantize=True):
    """Rebuild ``(built, params, spec)`` from an export dir — the raw
    builder object (flax Module or plain callable) plus deserialized
    params, WITHOUT wrapping into a signature apply fn.

    This is the entry for consumers that need the module itself rather
    than a fixed forward — e.g. autoregressive generation, which re-enters
    the model once per token through its kv cache.  int8-quantized exports
    dequantize eagerly by default (callers that apply the module directly
    expect float leaves); pass ``dequantize=False`` to receive the STORED
    tree — every jitted decode entry point accepts the quantized form
    as-is (decode._params_view dequantizes inline, fused into the matmul
    operand read), which is how quantized serving avoids ever
    materializing the full-width tree (serve.GenerateService._load_lm).
    """
    spec = _read_spec(export_dir)
    built = _resolve_builder(spec["builder"])(**spec["builder_kwargs"])
    params = _restore_params(export_dir)
    if dequantize and spec.get("quantized") == "int8":
        from . import quantize as quantize_mod
        params = quantize_mod.dequantize_tree(
            params, dtype=spec.get("dequant_dtype"))
    return built, params, spec


def load_saved_model(export_dir, signature_def_key=None):
    """Load ``(apply_fn, params, signature)`` from an export dir.

    ``apply_fn(params, *inputs)`` is the raw forward; callers jit it.  Maps
    the reference's ``tf.saved_model.load`` + signature lookup
    (pipeline.py:596-613).
    """
    spec, signature = read_signature(export_dir, signature_def_key)

    built = _resolve_builder(spec["builder"])(**spec["builder_kwargs"])
    if hasattr(built, "apply") and hasattr(built, "init"):  # flax Module
        model = built

        def apply_fn(params, *inputs):
            return model.apply({"params": params}, *inputs)
    else:
        apply_fn = built

    params = _restore_params(export_dir)
    if spec.get("quantized") == "int8":
        from . import quantize as quantize_mod
        inner_apply = apply_fn
        deq_dtype = spec.get("dequant_dtype")

        def apply_fn(qtree, *inputs):   # dequant fuses under the caller's jit
            return inner_apply(
                quantize_mod.dequantize_tree(qtree, dtype=deq_dtype),
                *inputs)
    return apply_fn, params, signature


def coerce_inputs(signature, columns):
    """Reshape flat tabular columns into the signature's tensor shapes.

    ``columns`` is {input_name: list_of_row_values}; each row value may be a
    flat list that the recorded shape (leading batch dim excluded, -1 ok)
    restores to its tensor form — the reference's shape-coercion for Spark's
    flat arrays (pipeline.py:615-630).
    """
    import numpy as np

    arrays = []
    for name, meta in signature["inputs"].items():
        if name not in columns:
            raise KeyError(f"input column {name!r} missing; have {sorted(columns)}")
        arr = np.asarray(columns[name], dtype=meta.get("dtype") or None)
        shape = meta.get("shape")
        if shape:
            arr = arr.reshape((arr.shape[0],) + tuple(int(d) for d in shape))
        arrays.append(arr)
    return arrays
