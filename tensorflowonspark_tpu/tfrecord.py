"""TFRecord + tf.train.Example I/O, owned natively by the framework.

The reference delegated this format to the prebuilt tensorflow-hadoop jar
(SURVEY.md §2.2: lib/tensorflow-hadoop-1.0-SNAPSHOT.jar, used via
dfutil.py:39,63) and to TF's protobuf classes.  This framework owns both
layers so the data path has no TF/JVM dependency:

- record framing: uint64 length (LE) + masked CRC32C of the length + payload
  + masked CRC32C of the payload (the public TFRecord wire format),
- a minimal protobuf wire-format codec for the `tf.train.Example` message
  family (Example/Features/Feature/BytesList/FloatList/Int64List), writing
  the same field numbers as the public schema so files interoperate with
  TF and every other TFRecord reader,
- an optional C++ fast path (native/tfrecord_io.cc via ctypes) for framing +
  CRC; this module falls back to pure Python when the .so is absent.

Interop is tested against TensorFlow itself as an oracle
(tests/test_tfrecord.py).
"""
import io
import logging
import os
import struct

logger = logging.getLogger(__name__)

# --------------------------------------------------------------------------
# CRC32C (Castagnoli).  Table-driven pure-Python fallback; the native lib
# replaces this on the hot path.
# --------------------------------------------------------------------------

_CRC_TABLE = []


def _build_crc_table():
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_crc_table()


def crc32c(data):
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_MASK_DELTA = 0xA282EAD8


def masked_crc32c(data):
    crc = _crc_fn(data)
    return ((crc >> 15) | (crc << 17)) + _MASK_DELTA & 0xFFFFFFFF


# --------------------------------------------------------------------------
# Native acceleration (ctypes; optional)
# --------------------------------------------------------------------------

_native = None


def _load_native():
    global _native, _crc_fn
    import ctypes
    native_dir = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "native"))
    so = os.path.join(native_dir, "libtfrecord_io.so")
    if not os.path.exists(so):
        # The .so is a build artifact (not committed); build it once from
        # source, best-effort.  Pure-Python fallback covers failure.
        src = os.path.join(native_dir, "tfrecord_io.cc")
        if os.path.exists(src):
            import subprocess
            try:
                subprocess.run(["make", "-C", native_dir], check=True,
                               capture_output=True, timeout=120)
            except Exception as e:
                logger.info("native tfrecord build skipped: %s", e)
    if not os.path.exists(so):
        return None
    try:
        lib = ctypes.CDLL(so)
        lib.tfr_crc32c.restype = ctypes.c_uint32
        lib.tfr_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.tfr_index_records.restype = ctypes.c_long
        lib.tfr_index_records.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t, ctypes.c_int]
        lib.tfr_index_file.restype = ctypes.c_long
        lib.tfr_index_file.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t, ctypes.c_int]
        lib.tfr_frame_record.restype = ctypes.c_size_t
        lib.tfr_frame_record.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
        lib.tfr_read_column.restype = ctypes.c_long
        lib.tfr_read_column.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_int]
        _native = lib

        def fast_crc(data):
            b = bytes(data)
            return lib.tfr_crc32c(b, len(b))

        _crc_fn = fast_crc
        logger.info("tfrecord native acceleration loaded from %s", so)
        return lib
    except OSError as e:
        logger.warning("could not load native tfrecord lib: %s", e)
        return None


def _native_index_file(path, size, verify_crc=True):
    """Index a TFRecord file with the C library (mmap'd and CRC-checked
    entirely in C); returns (offsets, lengths)."""
    import ctypes
    # worst case: empty records are 16 bytes each
    max_records = max(size // 16, 1)
    offsets = (ctypes.c_uint64 * max_records)()
    lengths = (ctypes.c_uint64 * max_records)()
    count = _native.tfr_index_file(os.fsencode(path), offsets, lengths,
                                   max_records, 1 if verify_crc else 0)
    if count == -1:
        raise IOError("TFRecord length CRC mismatch (corrupt file)")
    if count == -2:
        raise IOError("TFRecord payload CRC mismatch (corrupt file)")
    if count == -3:
        raise IOError("truncated TFRecord file")
    if count == -5:
        raise IOError(f"cannot read {path}")
    if count < 0:
        raise IOError(f"TFRecord index error {count}")
    return offsets[:count], lengths[:count]


_crc_fn = crc32c
_load_native()


# --------------------------------------------------------------------------
# Record framing
# --------------------------------------------------------------------------

class TFRecordWriter:
    """Writes framed records to a file-like or path.

    `compression="gzip"` writes a gzip stream (the Hadoop/tf.data
    ``TFRecordOptions(compression_type="GZIP")`` format; auto-enabled for
    paths ending in ``.gz``) — the reader auto-detects it by magic bytes.
    """

    def __init__(self, path_or_file, compression=None, index=False):
        # All argument validation happens BEFORE the 'wb' open: opening
        # first would truncate an existing file on a call that then fails.
        if compression not in (None, "", "gzip"):
            raise ValueError(f"unsupported compression {compression!r}")
        is_file_like = hasattr(path_or_file, "write")
        if not is_file_like and compression is None \
                and str(path_or_file).endswith(".gz"):
            compression = "gzip"
        if index and is_file_like:
            raise ValueError("index=True needs a path (the sidecar is "
                             "written next to the data file)")
        if index and compression == "gzip":
            raise ValueError("gzip streams have no random access; "
                             "index=True requires an uncompressed file")
        if is_file_like:
            self._raw = path_or_file
            self._own = False
        else:
            from . import fsio
            self._raw = fsio.fopen(path_or_file, "wb")
            self._own = True
        if compression == "gzip":
            import gzip
            self._f = gzip.GzipFile(fileobj=self._raw, mode="wb")
            self._gz = True
        else:
            self._f = self._raw
            self._gz = False
        # Sidecar index accumulation: payload offsets/lengths tracked as
        # frames are written (we own the framing, so counting is exact).
        self._path = None if hasattr(path_or_file, "write") else path_or_file
        self._index = ([], []) if index else None
        self._pos = 0

    def write(self, record_bytes):
        data = bytes(record_bytes)
        if self._index is not None:
            self._index[0].append(self._pos + 12)   # payload offset
            self._index[1].append(len(data))
        self._pos += len(data) + 16
        if _native is not None:
            import ctypes
            out = ctypes.create_string_buffer(len(data) + 16)
            n = _native.tfr_frame_record(data, len(data), out)
            self._f.write(out.raw[:n])
            return
        length = struct.pack("<Q", len(data))
        self._f.write(length)
        self._f.write(struct.pack("<I", masked_crc32c(length)))
        self._f.write(data)
        self._f.write(struct.pack("<I", masked_crc32c(data)))

    def flush(self):
        self._f.flush()
        if self._gz:
            self._raw.flush()

    def close(self):
        if self._gz:
            self._f.close()         # writes the gzip trailer; leaves _raw open
        if self._own:
            self._raw.close()
        if self._index is not None:
            _write_index_sidecar(default_index_path(self._path), self._path,
                                 self._pos, self._index[0], self._index[1])
            self._index = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _head_is_gzip(head):
    """True when a 12-byte file head is a gzip stream and NOT a plain
    TFRecord.

    The gzip magic (1f 8b) can collide with the little-endian uint64
    length prefix of a plain record, so a valid plain-TFRecord header
    (length CRC checks out — 2^-32 false-positive odds for real gzip
    bytes) wins over the magic."""
    if len(head) == 12:
        (len_crc,) = struct.unpack("<I", head[8:12])
        if masked_crc32c(head[:8]) == len_crc:
            return False            # valid plain TFRecord frame header
    return head[:2] == b"\x1f\x8b"


def _is_gzip(path):
    from . import fsio
    with fsio.fopen(path, "rb") as f:
        return _head_is_gzip(f.read(12))


def read_records(path_or_file, verify_crc=True):
    """Yield raw record payloads from a TFRecord file.

    Gzip-compressed files (tf.data GZIP / Hadoop codec) are auto-detected
    by magic bytes and streamed through the pure-Python parser.  Plain
    files use the native indexer over an mmapped file when available (one
    pass of C CRC + zero-copy slicing); falls back to the pure-Python
    frame parser.
    """
    from . import fsio

    if not hasattr(path_or_file, "read") and fsio.is_remote(path_or_file):
        # ONE remote open serves sniff + parse (each open is a round trip
        # on object stores); gzip wraps the same handle
        with fsio.fopen(path_or_file, "rb") as raw:
            head = raw.read(12)
            raw.seek(0)
            if _head_is_gzip(head):
                import gzip
                with gzip.GzipFile(fileobj=raw, mode="rb") as gz:
                    yield from read_records(gz, verify_crc=verify_crc)
            else:
                yield from read_records(raw, verify_crc=verify_crc)
        return
    if not hasattr(path_or_file, "read") and _is_gzip(path_or_file):
        import gzip
        with fsio.fopen(path_or_file, "rb") as raw:
            with gzip.GzipFile(fileobj=raw, mode="rb") as gz:
                yield from read_records(gz, verify_crc=verify_crc)
        return
    if _native is not None and not hasattr(path_or_file, "read"):
        path = fsio.local_path(path_or_file)
        size = os.path.getsize(path)
        if size == 0:
            return
        # One C pass mmaps + CRC-checks + indexes the file, then records are
        # streamed with seek/read — O(record) resident memory for any shard
        # size, and CRC cost stays in native code.  (Local files only; remote
        # paths stream through the Python parser above.)
        offsets, lengths = _native_index_file(path, size, verify_crc)
        with open(path, "rb") as f:
            for off, ln in zip(offsets, lengths):
                f.seek(off)
                yield f.read(ln)
        return
    f = path_or_file if hasattr(path_or_file, "read") \
        else fsio.fopen(path_or_file, "rb")
    try:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise IOError("truncated TFRecord header")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if verify_crc and masked_crc32c(header[:8]) != len_crc:
                raise IOError("TFRecord length CRC mismatch (corrupt file)")
            data = f.read(length)
            if len(data) < length:
                raise IOError("truncated TFRecord payload")
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4:
                raise IOError("truncated TFRecord payload CRC")
            (data_crc,) = struct.unpack("<I", crc_bytes)
            if verify_crc and masked_crc32c(data) != data_crc:
                raise IOError("TFRecord payload CRC mismatch (corrupt file)")
            yield data
    finally:
        if not hasattr(path_or_file, "read"):
            f.close()


# --------------------------------------------------------------------------
# Minimal protobuf wire codec for tf.train.Example
#
# Schema (public field numbers):
#   Example    { Features features = 1 }
#   Features   { map<string, Feature> feature = 1 }
#   Feature    { BytesList bytes_list = 1 | FloatList float_list = 2 |
#                Int64List int64_list = 3 }
#   BytesList  { repeated bytes value = 1 }
#   FloatList  { repeated float value = 1 [packed] }
#   Int64List  { repeated int64 value = 1 [packed] }
# --------------------------------------------------------------------------

def _write_varint(buf, value):
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _write_tag(buf, field, wire_type):
    _write_varint(buf, (field << 3) | wire_type)


def _write_len_delim(buf, field, payload):
    _write_tag(buf, field, 2)
    _write_varint(buf, len(payload))
    buf.extend(payload)


def _zigzagless_int64(v):
    # int64 fields use two's-complement varints (10 bytes when negative)
    return v & 0xFFFFFFFFFFFFFFFF


def encode_feature(values):
    """Encode one Feature from a list of python values (homogeneous)."""
    buf = bytearray()
    if not values:
        # empty bytes_list by convention
        _write_len_delim(buf, 1, b"")
        return bytes(buf)
    first = values[0]
    inner = bytearray()
    if isinstance(first, (bytes, bytearray, str)):
        for v in values:
            if isinstance(v, str):
                v = v.encode("utf-8")
            _write_len_delim(inner, 1, bytes(v))
        _write_len_delim(buf, 1, bytes(inner))       # bytes_list
    elif isinstance(first, float):
        packed = struct.pack(f"<{len(values)}f", *values)
        _write_len_delim(inner, 1, packed)           # packed floats
        _write_len_delim(buf, 2, bytes(inner))       # float_list
    elif isinstance(first, (int, bool)):
        for v in values:
            _write_varint(inner, _zigzagless_int64(int(v)))
        packed = bytearray()
        _write_tag(packed, 1, 2)
        _write_varint(packed, len(inner))
        packed.extend(inner)                          # packed int64s
        _write_len_delim(buf, 3, bytes(packed))      # int64_list
    else:
        raise TypeError(f"unsupported feature value type {type(first)!r}")
    return bytes(buf)


def encode_example(feature_dict):
    """Encode {name: list-of-values | scalar | bytes} into Example bytes."""
    features_buf = bytearray()
    for name in sorted(feature_dict):
        values = feature_dict[name]
        if isinstance(values, (bytes, bytearray, str)) or not hasattr(
                values, "__iter__"):
            values = [values]
        else:
            values = list(values)
        feat = encode_feature(values)
        entry = bytearray()
        _write_len_delim(entry, 1, name.encode("utf-8"))   # map key
        _write_len_delim(entry, 2, feat)                   # map value
        _write_len_delim(features_buf, 1, bytes(entry))    # Features.feature
    example = bytearray()
    _write_len_delim(example, 1, bytes(features_buf))      # Example.features
    return bytes(example)


def _read_varint(data, pos):
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(data):
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = _read_varint(data, pos)
        field, wt = key >> 3, key & 7
        if wt == 2:
            length, pos = _read_varint(data, pos)
            yield field, data[pos:pos + length]
            pos += length
        elif wt == 0:
            value, pos = _read_varint(data, pos)
            yield field, value
        elif wt == 5:
            yield field, data[pos:pos + 4]
            pos += 4
        elif wt == 1:
            yield field, data[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")


def _signed64(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def decode_feature(data):
    """Decode one Feature into (kind, values) with kind in
    {'bytes','float','int64'}."""
    for field, payload in _iter_fields(data):
        if field == 1:      # BytesList
            return "bytes", [bytes(v) for f, v in _iter_fields(payload) if f == 1]
        if field == 2:      # FloatList (packed or repeated)
            values = []
            for f, v in _iter_fields(payload):
                if f == 1:
                    if isinstance(v, (bytes, bytearray, memoryview)):
                        values.extend(struct.unpack(f"<{len(v)//4}f", v))
                    else:
                        values.append(struct.unpack("<f", struct.pack("<I", v))[0])
            return "float", values
        if field == 3:      # Int64List
            values = []
            for f, v in _iter_fields(payload):
                if f == 1:
                    if isinstance(v, (bytes, bytearray, memoryview)):
                        pos = 0
                        while pos < len(v):
                            value, pos = _read_varint(v, pos)
                            values.append(_signed64(value))
                    else:
                        values.append(_signed64(v))
            return "int64", values
    return "bytes", []


def decode_example(data):
    """Decode Example bytes into {name: (kind, values)}."""
    out = {}
    for field, features in _iter_fields(data):
        if field != 1:
            continue
        for f, entry in _iter_fields(features):
            if f != 1:
                continue
            name, feat = None, b""
            for ef, ev in _iter_fields(entry):
                if ef == 1:
                    name = bytes(ev).decode("utf-8")
                elif ef == 2:
                    feat = ev
            if name is not None:
                out[name] = decode_feature(feat)
    return out


# --------------------------------------------------------------------------
# Convenience: dict-of-values <-> files
# --------------------------------------------------------------------------

def write_examples(path, dicts, compression=None, index=False):
    """Write an iterable of {name: values} dicts as a TFRecord file
    (gzip-compressed when `compression="gzip"` or the path ends in .gz;
    `index=True` also writes the random-access sidecar index)."""
    count = 0
    with TFRecordWriter(path, compression=compression, index=index) as w:
        for d in dicts:
            w.write(encode_example(d))
            count += 1
    return count


def read_examples(path, verify_crc=True):
    """Yield decoded {name: (kind, values)} dicts from a TFRecord file."""
    for record in read_records(path, verify_crc=verify_crc):
        yield decode_example(record)


_COLUMN_ERRORS = {
    -1: "TFRecord length CRC mismatch (corrupt file)",
    -2: "TFRecord payload CRC mismatch (corrupt file)",
    -3: "truncated TFRecord file",
    -5: "cannot read file",
    -6: "ragged feature: value count differs between records",
    -7: "feature missing from a record",
    -8: "feature holds a different kind than the first record",
    -9: "malformed Example payload",
}


def read_column(path, name, verify_crc=True):
    """Decode ONE fixed-length numeric feature column of a whole TFRecord
    file of Example records into a numpy array [n_records, feat_len]
    (float32 for FloatList features, int64 for Int64List).

    Local uncompressed files decode in a single native pass (mmap + CRC +
    proto walk, no per-record Python objects — the C++ analog of the
    reference's JVM DFUtil row decode); remote/gzip paths fall back to
    the Python codec.  Ragged features (per-record length changes),
    missing features, and kind mismatches raise IOError/TypeError.
    """
    import numpy as np

    from . import fsio

    first = next(read_examples(path, verify_crc=verify_crc), None)
    if first is None:
        raise ValueError(f"{path}: empty TFRecord file")
    if name not in first:
        raise IOError(f"{path}: feature {name!r} missing from a record")
    kind, values = first[name]
    if kind == "bytes":
        raise TypeError(f"feature {name!r} is a BytesList; read_column "
                        "decodes numeric (float/int64) columns")
    feat_len = len(values)
    proto_kind = 2 if kind == "float" else 3
    np_dtype = np.float32 if kind == "float" else np.int64

    if _native is not None and not fsio.is_remote(path) \
            and not _is_gzip(path) and feat_len > 0:
        import ctypes

        local = fsio.local_path(path)
        # row-count bound: every record costs >= 16 framing bytes plus at
        # least one wire byte per value, so size//(16+feat_len) bounds the
        # record count without tying the allocation to the 16-byte
        # worst case (which would reserve feat_len*8 bytes PER FILE BYTE
        # for wide columns)
        n_max = max(os.path.getsize(local) // (16 + feat_len), 1)
        out = np.empty((n_max, feat_len), np_dtype)
        rc = _native.tfr_read_column(
            os.fsencode(local), name.encode(), proto_kind,
            out.ctypes.data_as(ctypes.c_void_p), feat_len, n_max,
            1 if verify_crc else 0)
        if rc == -8:
            raise TypeError(_COLUMN_ERRORS[-8] + f" (feature {name!r})")
        if rc < 0:
            raise IOError(f"{path}: " + _COLUMN_ERRORS.get(
                int(rc), f"column decode error {rc}"))
        return out[:rc].copy()

    rows = []
    for ex in read_examples(path, verify_crc=verify_crc):
        if name not in ex:
            raise IOError(f"{path}: feature {name!r} missing from a record")
        k, v = ex[name]
        if k != kind:
            raise TypeError(_COLUMN_ERRORS[-8] + f" (feature {name!r})")
        if len(v) != feat_len:
            raise IOError(f"{path}: " + _COLUMN_ERRORS[-6])
        rows.append(v)
    return np.asarray(rows, np_dtype).reshape(len(rows), feat_len)


# --------------------------------------------------------------------------
# Indexed random access (the ArrayRecord-style capability, SURVEY.md §2.2:
# the native data layer should own "TFRecord + ArrayRecord I/O").
#
# A TFRecord stream is sequential-only: record N is reachable only by
# scanning records 0..N-1, so global shuffling and balanced record-granular
# sharding require either a full pass per epoch or an index.  This section
# adds the index as a SIDECAR file (`<data>.idx`) so the data file stays a
# byte-for-byte standard TFRecord, readable by TF, Hadoop, and every other
# TFRecord consumer — unlike a footer-based container, nothing about the
# wire format changes.
#
# Sidecar format (little-endian):
#   8B   magic  b"TFRIDX2\0"
#   u64  data file size when indexed   (staleness check)
#   u64  record count N
#   u32  data fingerprint: masked CRC32C over the data file's first and
#        last min(64, size) bytes  (catches same-size rewrites)
#   N*u64  payload offsets
#   N*u64  payload lengths
#   u32  masked CRC32C over everything after the magic
#
# The index is rebuildable from the data alone (one native mmap+CRC pass
# locally, one streaming pass remotely), so a missing or stale sidecar
# degrades to a scan, never an error.
# --------------------------------------------------------------------------

INDEX_MAGIC = b"TFRIDX2\0"
_INDEX_MAGIC_V1 = b"TFRIDX1\0"   # still readable: size-only staleness
INDEX_SUFFIX = ".idx"


def default_index_path(path):
    """Sidecar index path for a TFRecord data file."""
    return str(path) + INDEX_SUFFIX


def index_records(path, verify_crc=True):
    """Scan a TFRecord file and return (offsets, lengths) of every record
    payload.  Local files use the native one-pass mmap indexer; remote
    (fsspec) paths stream through the Python frame parser."""
    from . import fsio

    if _is_gzip(path):
        raise ValueError(f"{path}: gzip TFRecord streams have no random "
                         "access (no stable byte offsets); store shards "
                         "uncompressed to index them")
    if _native is not None and not fsio.is_remote(path):
        local = fsio.local_path(path)
        size = os.path.getsize(local)
        if size == 0:
            return [], []
        offs, lens = _native_index_file(local, size, verify_crc)
        return list(offs), list(lens)
    offsets, lengths = [], []
    with fsio.fopen(path, "rb") as f:
        pos = 0
        while True:
            header = f.read(12)
            if not header:
                break
            if len(header) < 12:
                raise IOError("truncated TFRecord header")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if verify_crc and masked_crc32c(header[:8]) != len_crc:
                raise IOError("TFRecord length CRC mismatch (corrupt file)")
            data = f.read(length)
            crc_bytes = f.read(4)
            if len(data) < length or len(crc_bytes) < 4:
                raise IOError("truncated TFRecord payload")
            if verify_crc and \
                    masked_crc32c(data) != struct.unpack("<I", crc_bytes)[0]:
                raise IOError("TFRecord payload CRC mismatch (corrupt file)")
            offsets.append(pos + 12)
            lengths.append(length)
            pos += 12 + length + 4
    return offsets, lengths


def _data_fingerprint(path, size):
    """CRC over the data file's head+tail bytes.  Catches the rewrite the
    size check alone cannot: a data file replaced by one of the SAME byte
    size, which would otherwise serve wrong payloads silently under
    verify_crc=False (two ranged reads; cheap even on remote FS)."""
    from . import fsio

    if size <= 0:
        return 0
    n = min(64, size)
    with fsio.fopen(path, "rb") as f:
        head = f.read(n)
        f.seek(max(0, size - n))
        tail = f.read(n)
    return masked_crc32c(head + tail)


def _write_index_sidecar(index_path, data_path, data_size, offsets, lengths):
    from . import fsio

    body = io.BytesIO()
    body.write(struct.pack("<QQI", data_size, len(offsets),
                           _data_fingerprint(data_path, data_size)))
    body.write(struct.pack(f"<{len(offsets)}Q", *offsets))
    body.write(struct.pack(f"<{len(lengths)}Q", *lengths))
    payload = body.getvalue()
    with fsio.fopen(index_path, "wb") as f:
        f.write(INDEX_MAGIC)
        f.write(payload)
        f.write(struct.pack("<I", masked_crc32c(payload)))


def write_index(path, index_path=None, verify_crc=True):
    """Build and persist the sidecar index for an existing TFRecord file.
    Returns (offsets, lengths)."""
    from . import fsio

    offsets, lengths = index_records(path, verify_crc=verify_crc)
    _write_index_sidecar(index_path or default_index_path(path), path,
                         fsio.getsize(path), offsets, lengths)
    return offsets, lengths


def read_index(path, index_path=None):
    """Load the sidecar index for `path`.  Returns (offsets, lengths), or
    None when the sidecar is missing, corrupt, or stale (data file size OR
    head/tail content fingerprint changed since it was written) — callers
    rebuild via index_records()."""
    from . import fsio

    idx = index_path or default_index_path(path)
    if not fsio.exists(idx):
        return None
    try:
        with fsio.fopen(idx, "rb") as f:
            blob = f.read()
        magic = blob[:len(INDEX_MAGIC)]
        v1 = magic == _INDEX_MAGIC_V1   # pre-fingerprint sidecars stay
        # readable with their original (size-only) staleness semantics —
        # a format bump must not degrade existing datasets to full scans
        if len(blob) < len(INDEX_MAGIC) + (20 if v1 else 24) \
                or (magic != INDEX_MAGIC and not v1):
            return None
        payload, (crc,) = blob[8:-4], struct.unpack("<I", blob[-4:])
        if masked_crc32c(payload) != crc:
            logger.warning("ignoring corrupt index sidecar %s", idx)
            return None
        header = 16 if v1 else 20
        if v1:
            data_size, count = struct.unpack_from("<QQ", payload, 0)
            fingerprint = None
        else:
            data_size, count, fingerprint = struct.unpack_from(
                "<QQI", payload, 0)
        if header + 16 * count != len(payload):
            return None
        if data_size != fsio.getsize(path) or (
                fingerprint is not None
                and fingerprint != _data_fingerprint(path, data_size)):
            logger.info("index sidecar %s is stale; reindexing", idx)
            return None
        offsets = list(struct.unpack_from(f"<{count}Q", payload, header))
        lengths = list(
            struct.unpack_from(f"<{count}Q", payload, header + 8 * count))
        return offsets, lengths
    except (OSError, struct.error):
        return None


class IndexedTFRecordFile:
    """Random-access reader over one TFRecord shard.

    Uses the sidecar index when present and fresh, else builds the index in
    memory with one scan.  Works over any fsspec filesystem that supports
    seek (local, gs://, hdfs://, s3://, memory:// ...): each `read(i)` is
    one ranged read, and `read_range` fetches a contiguous run of records
    with a single ranged read — the unit the global-shuffle Dataset root
    reads by block.

    This is the capability the ArrayRecord format exists for; here the data
    file stays a standard TFRecord and random access lives in the sidecar.
    """

    def __init__(self, path, index_path=None, verify_crc=True):
        self._path = path
        self._verify = verify_crc
        loaded = read_index(path, index_path)
        if loaded is None:
            loaded = index_records(path, verify_crc=verify_crc)
        self._offsets, self._lengths = loaded
        self._f = None                  # opened lazily on first read

    def _file(self):
        if self._f is None:
            from . import fsio
            self._f = fsio.fopen(self._path, "rb")
        return self._f

    def release(self):
        """Close the underlying file handle, keeping the index; the next
        read reopens transparently.  Lets callers iterate thousands of
        shard files without holding thousands of fds (the Dataset root
        keeps an LRU of open readers and releases the rest)."""
        if self._f is not None:
            self._f.close()
            self._f = None

    def __len__(self):
        return len(self._offsets)

    def read(self, i):
        """Record payload `i` (one seek + one read)."""
        off, ln = self._offsets[i], self._lengths[i]   # IndexError on bad i
        f = self._file()
        f.seek(off)
        data = f.read(ln + 4)
        if len(data) < ln + 4:
            raise IOError(f"{self._path}: truncated record {i}")
        payload, (crc,) = data[:ln], struct.unpack("<I", data[ln:])
        if self._verify and masked_crc32c(payload) != crc:
            raise IOError(f"{self._path}: payload CRC mismatch at record {i}")
        return payload

    __getitem__ = read

    def read_range(self, start, count):
        """Payloads of records [start, start+count) via ONE ranged read."""
        if count <= 0:
            return []
        last = start + count - 1
        span_start = self._offsets[start] - 12       # frame header start
        span_end = self._offsets[last] + self._lengths[last] + 4
        f = self._file()
        f.seek(span_start)
        blob = f.read(span_end - span_start)
        if len(blob) < span_end - span_start:
            raise IOError(f"{self._path}: truncated record range "
                          f"[{start}, {start + count})")
        out = []
        for i in range(start, start + count):
            lo = self._offsets[i] - span_start
            payload = blob[lo:lo + self._lengths[i]]
            if self._verify:
                (crc,) = struct.unpack_from(
                    "<I", blob, lo + self._lengths[i])
                if masked_crc32c(payload) != crc:
                    raise IOError(f"{self._path}: payload CRC mismatch at "
                                  f"record {i}")
            out.append(payload)
        return out

    def example(self, i):
        """Decoded `{name: (kind, values)}` dict for record `i`."""
        return decode_example(self.read(i))

    def close(self):
        self.release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
