"""DataFrame / iterator ⇄ TFRecord interchange (maps reference dfutil.py:1-212).

The reference converts Spark DataFrames to `tf.train.Example` TFRecords via
the tensorflow-hadoop jar (dfutil.py:29-81) with schema inference and a
`binary_features` hint to disambiguate bytes vs string (dfutil.py:134-168).
This build owns the format natively (tfrecord.py + native/tfrecord_io.cc)
and works at two levels:

- iterator level (no Spark needed): `write_tfrecords` / `read_tfrecords` /
  `infer_schema` over dicts of values — this is also what feeds DataFeed.
- Spark level (gated on pyspark): `saveAsTFRecords` / `loadTFRecords` with
  the reference's semantics — each executor writes its partition as a
  `part-rXXXXX` shard, schema is inferred from the first record, and
  `loadedDF` tracks provenance for `isLoadedDF` (reference: dfutil.py:15-26).
"""
import logging
import os

from . import tfrecord

logger = logging.getLogger(__name__)

# DataFrames produced by loadTFRecords, keyed by id (reference: dfutil.py:15-26)
loadedDF = {}


def isLoadedDF(df):
    """True if `df` came from loadTFRecords (reference: dfutil.py:20-26)."""
    return id(df) in loadedDF


# --------------------------------------------------------------------------
# Schema: {column: type} with types 'int64' | 'float32' | 'binary' |
# 'string' | 'array<int64>' | 'array<float32>' | 'array<binary>' | 'array<string>'
# --------------------------------------------------------------------------

_SCALAR_KINDS = {"int64", "float32", "binary", "string"}


def infer_schema(row, binary_features=()):
    """Infer {column: type} from one example row (dict of python values).

    Maps reference infer_schema (dfutil.py:134-168): bytes default to
    'string' unless named in `binary_features` (TFRecords don't distinguish).
    """
    schema = {}
    for name, value in row.items():
        is_array = isinstance(value, (list, tuple))
        probe = value[0] if is_array and len(value) else value
        if isinstance(probe, bool):
            kind = "int64"
        elif isinstance(probe, int):
            kind = "int64"
        elif isinstance(probe, float):
            kind = "float32"
        elif isinstance(probe, (bytes, bytearray)):
            kind = "binary" if name in binary_features else "string"
        elif isinstance(probe, str):
            kind = "string"
        elif is_array and not len(value):
            kind = "float32"  # empty array: assume float (reference default)
        else:
            raise TypeError(f"cannot infer TFRecord type for column {name!r} "
                            f"value {value!r}")
        schema[name] = f"array<{kind}>" if is_array else kind
    return schema


def schema_from_example(example, binary_features=()):
    """Infer schema from a decoded example {name: (kind, values)}.

    Single-valued features map to scalars, multi-valued to arrays — the same
    first-record heuristic as the reference (dfutil.py:44-81).
    """
    schema = {}
    for name, (kind, values) in example.items():
        if kind == "bytes":
            col = "binary" if name in binary_features else "string"
        elif kind == "float":
            col = "float32"
        else:
            col = "int64"
        schema[name] = col if len(values) <= 1 else f"array<{col}>"
    return schema


def to_feature_dict(row, schema=None):
    """Convert a python row dict into encode_example-ready values."""
    out = {}
    for name, value in row.items():
        if isinstance(value, str):
            value = value.encode("utf-8")
        elif isinstance(value, (list, tuple)):
            value = [v.encode("utf-8") if isinstance(v, str) else v
                     for v in value]
        elif isinstance(value, bool):
            value = int(value)
        out[name] = value
    return out


def from_example(example, schema):
    """Decode {name: (kind, values)} into a python row dict per `schema`
    (maps reference fromTFExample, dfutil.py:171-212)."""
    row = {}
    for name, coltype in schema.items():
        kind, values = example.get(name, ("bytes", []))
        is_array = coltype.startswith("array<")
        base = coltype[6:-1] if is_array else coltype
        if base == "string":
            values = [v.decode("utf-8", "replace") if isinstance(v, bytes)
                      else v for v in values]
        elif base == "binary":
            values = [bytes(v) for v in values]
        elif base == "float32":
            values = [float(v) for v in values]
        elif base == "int64":
            values = [int(v) for v in values]
        if is_array:
            row[name] = values
        else:
            row[name] = values[0] if values else None
    return row


# --------------------------------------------------------------------------
# Iterator-level API (no Spark required)
# --------------------------------------------------------------------------

def write_tfrecords(rows, path, index=False):
    """Write an iterable of row dicts to one TFRecord file (``index=True``
    adds the random-access sidecar); returns count."""
    return tfrecord.write_examples(
        path, (to_feature_dict(r) for r in rows), index=index)


def read_tfrecords(path_or_dir, binary_features=(), schema=None):
    """Read rows back from a file or a directory of part files.

    Returns (rows, schema); schema is inferred from the first record unless
    given (the reference's loadTFRecords contract, dfutil.py:44-81).
    """
    from . import fsio
    if fsio.isdir(path_or_dir):
        paths = fsio.glob(fsio.join(path_or_dir, "part-*"))
        if not paths:
            paths = [p for p in fsio.glob(fsio.join(path_or_dir, "*"))
                     if fsio.isfile(p) and not
                     os.path.basename(p).startswith(("_", "."))]
        # random-access sidecars live next to the data shards
        paths = [p for p in paths
                 if not p.endswith(tfrecord.INDEX_SUFFIX)]
    else:
        paths = [path_or_dir]
    rows = []
    for p in paths:
        for example in tfrecord.read_examples(p):
            if schema is None:
                schema = schema_from_example(example, binary_features)
            rows.append(from_example(example, schema))
    return rows, (schema or {})


# --------------------------------------------------------------------------
# Spark-level API (gated)
# --------------------------------------------------------------------------

def saveAsTFRecords(df, output_dir, index=False):
    """Save a Spark DataFrame as sharded TFRecord files (maps reference
    saveAsTFRecords, dfutil.py:29-41 — but writes natively per executor
    instead of through the Hadoop output format).  ``index=True`` also
    writes each shard's random-access sidecar index, so downstream
    readers get Dataset.from_indexed_tfrecords' exact global shuffle
    without a rebuild scan."""
    columns = df.columns
    write_index = index

    def write_partition(index, iterator):
        # makedirs must run on the EXECUTOR, not the driver: on a multi-node
        # cluster the driver's filesystem is a different machine.  Remote
        # schemes (gs://, s3://, hdfs://, ...) write through fsio/fsspec —
        # the analog of the reference's Hadoop output format; plain local
        # paths land on a shared filesystem iff output_dir is one.
        from tensorflowonspark_tpu import fsio
        fsio.makedirs(output_dir)
        part = fsio.join(output_dir, f"part-r-{index:05d}")
        count = write_tfrecords(
            (dict(zip(columns, row)) for row in iterator), part,
            index=write_index)
        yield (index, count)

    counts = df.rdd.mapPartitionsWithIndex(write_partition).collect()
    total = sum(c for _, c in counts)
    logger.info("wrote %d records to %s in %d shards", total, output_dir,
                len(counts))
    return total


def loadTFRecords(sc, input_dir, binary_features=(), schema_hint=None):
    """Load TFRecord shards into a Spark DataFrame (maps reference
    loadTFRecords, dfutil.py:44-81).  `schema_hint` is {column: type} using
    this module's type strings."""
    from pyspark.sql import SparkSession

    from . import fsio

    spark = SparkSession.builder.getOrCreate()
    paths = [p for p in
             (fsio.glob(fsio.join(input_dir, "part-*")) or [input_dir])
             if not p.endswith(tfrecord.INDEX_SUFFIX)]

    # infer schema from the first record of the first shard
    schema = dict(schema_hint or {})
    if not schema:
        first = next(iter(tfrecord.read_examples(paths[0])), None)
        if first is None:
            raise ValueError(f"no records found under {input_dir}")
        schema = schema_from_example(first, binary_features)
    columns = sorted(schema)

    def read_shard(path):
        for example in tfrecord.read_examples(path):
            row = from_example(example, schema)
            yield tuple(row[c] for c in columns)

    rdd = sc.parallelize(paths, len(paths)).flatMap(read_shard)
    df = spark.createDataFrame(rdd, _spark_schema(schema, columns))
    loadedDF[id(df)] = input_dir
    return df


def _spark_schema(schema, columns):
    from pyspark.sql import types as T

    base = {"int64": T.LongType(), "float32": T.FloatType(),
            "binary": T.BinaryType(), "string": T.StringType()}

    fields = []
    for c in columns:
        t = schema[c]
        if t.startswith("array<"):
            fields.append(T.StructField(c, T.ArrayType(base[t[6:-1]])))
        else:
            fields.append(T.StructField(c, base[t]))
    return T.StructType(fields)
