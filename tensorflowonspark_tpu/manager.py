"""Per-executor IPC manager: named queues + a kv store.

Maps the reference's TFManager (reference: TFManager.py:14-83): a
`multiprocessing.managers.BaseManager` that proxies `JoinableQueue`s (named
'input'/'output'/'error'/'control') and a key-value dict between the feeder
process (producer), the JAX runtime process (consumer), and — for evaluator
nodes — the remote driver.

Modes (reference: TFManager.py:40-65):
- 'local'  — bound to loopback; reachable only from processes on this host.
- 'remote' — bound to all interfaces so the driver can push shutdown
  sentinels into control queues (reference: TFCluster.py:186-194).
"""
import logging
import multiprocessing as mp
from multiprocessing.managers import BaseManager

from . import util

logger = logging.getLogger(__name__)

# Server-process globals (exist only inside the manager server process;
# reference: TFManager.py:20-22).
_qdict = {}
_kdict = {}


def _get_queue(qname):
    if qname not in _qdict:
        # Raising (vs returning None) matters: BaseManager wraps every return
        # value in a proxy, so a None return would still look truthy.
        raise KeyError(qname)
    return _qdict[qname]


def _has_queue(qname):
    return qname in _qdict


def _get(key):
    return _kdict.get(key)


def _set(key, value):
    _kdict[key] = value


class QueueManager(BaseManager):
    """BaseManager exposing get_queue/get/set proxies (reference: TFManager.py:14-37)."""


QueueManager.register("get_queue", callable=_get_queue)
QueueManager.register("has_queue", callable=_has_queue)
QueueManager.register("get", callable=_get)
QueueManager.register("set", callable=_set)


def _init_server(queue_names):
    """Populate the queue dict INSIDE the manager server process.

    Using BaseManager's initializer (rather than pre-filling module globals in
    the parent) keeps this correct under the 'spawn' start method, where the
    server process re-imports this module and would otherwise see empty dicts.
    """
    for qname in queue_names:
        _qdict[qname] = mp.JoinableQueue()


def start(authkey, queues, mode="local"):
    """Start a manager server process holding `queues` (reference: TFManager.py:40-65).

    Returns the started manager; its reachable address is at `.address`.
    `authkey` is bytes (a uuid4 in practice) gating access.
    """
    if mode == "remote":
        addr = ("", 0)  # all interfaces; reachable by the driver
    else:
        addr = ("localhost", 0)
    mgr = QueueManager(address=addr, authkey=authkey)
    mgr.start(initializer=_init_server, initargs=(list(queues),))

    host = util.get_ip_address() if mode == "remote" else "localhost"
    # mgr.address gives ('', port) in remote mode; substitute a routable host.
    port = mgr.address[1]
    mgr._tfos_addr = (host, port)
    # CRITICAL: keep a module-global reference.  BaseManager registers a
    # weakref-triggered finalizer that sends the server a shutdown message as
    # soon as the manager OBJECT is garbage-collected — so a manager held
    # only in a local variable dies with the enclosing frame.  The reference
    # relied on the same trick (module global `mgr`, TFManager.py:20-22).
    _started_managers.append(mgr)
    logger.info("started %s queue manager on %s (queues=%s)", mode, mgr._tfos_addr, queues)
    return mgr


_started_managers = []


def get_value(mgr, key):
    """Unwrap a kv value from its AutoProxy (proxies str-ify with quotes)."""
    proxy = mgr.get(key)
    return proxy._getvalue() if proxy is not None else None


def connect(addr, authkey):
    """Connect to a running manager (reference: TFManager.py:68-83).

    Sets the connecting process's authkey first — required by multiprocessing
    when the connecting process didn't inherit it.
    """
    if not isinstance(authkey, bytes):
        authkey = bytes(authkey)
    mp.current_process().authkey = authkey
    mgr = QueueManager(address=(addr[0], int(addr[1])), authkey=authkey)
    mgr.connect()
    return mgr
