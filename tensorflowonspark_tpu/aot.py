"""AOT (ahead-of-time) compiled inference artifacts + the native PJRT runner.

The reference serves models from executor JVMs through the TF Java/JNI bridge
(reference: src/main/scala/com/yahoo/tensorflowonspark/TFModel.scala:24-29
SavedModelBundle cache, :245-292 Session.runner feed/fetch;
Inference.scala:52-79 CLI). The TPU-native equivalent serializes the jitted
forward function to **StableHLO** (via jax.export) at fixed serving batch
sizes and executes it through one of two engines:

- ``jax``  — deserialize + call in-process (always available);
- ``native`` — the C++ PJRT runner (native/pjrt_runner.cc) loaded over
  ctypes, which compiles the StableHLO against any PJRT plugin
  (libtpu.so on TPU hosts; the mock plugin in tests). This path needs NO
  Python model code at serving time — like the reference's JVM bundle.

Artifact layout under ``<export_dir>/aot/``:
  model_b{N}.jexport        jax.export serialized artifact (jax engine)
  model_b{N}.stablehlo.mlir StableHLO module text (native engine)
  compile_options.pb        serialized CompileOptionsProto (native engine)
  aot_spec.json             {batch_sizes, inputs, outputs, platforms}
"""
import ctypes
import json
import logging
import os

logger = logging.getLogger(__name__)

AOT_DIR = "aot"
SPEC_FILE = "aot_spec.json"
PLUGIN_ENV = "TFOS_TPU_PJRT_PLUGIN"

# numpy dtype name -> PJRT_Buffer_Type (pjrt_c_api.h PJRT_Buffer_Type enum)
_PJRT_DTYPE = {
    "bool": 1, "int8": 2, "int16": 3, "int32": 4, "int64": 5,
    "uint8": 6, "uint16": 7, "uint32": 8, "uint64": 9,
    "float16": 10, "float32": 11, "float64": 12, "bfloat16": 13,
}
_PJRT_DTYPE_INV = {v: k for k, v in _PJRT_DTYPE.items()}


# --------------------------------------------------------------------------
# Export
# --------------------------------------------------------------------------

def export_aot(export_dir, apply_fn, params, signature, batch_sizes=(1, 64),
               platforms=("cpu", "tpu"), matmul_precision=None):
    """Serialize ``apply_fn(params, *inputs)`` at fixed batch sizes.

    Params are closed over (baked into the module as constants) so the
    artifact is self-contained — the serving side needs no model code and no
    param files, mirroring the reference's SavedModelBundle.
    ``signature`` uses the export.py schema ({"inputs": {name: {"shape",
    "dtype"}}, "outputs": [...]}); shapes exclude the batch dim.

    ``matmul_precision`` ("highest"/"float32" etc.) pins the dot/conv
    precision INTO the artifact: TPU compilers lower default-precision
    f32 matmuls to bf16 passes, so an artifact exported without this
    only matches a float32 host reference to ~bf16 tolerance (measured
    on a real chip — BASELINE.md round 5).

    One artifact is written PER platform (jax.export cross-lowers, so a CPU
    host can export for TPU serving): single-platform modules keep the plain
    ``main(inputs)`` calling convention the native PJRT runner expects
    (a combined multi-platform export would add a platform-index argument).
    """
    import jax
    import jax.numpy as jnp
    from jax import export as jexp

    aot_dir = os.path.join(export_dir, AOT_DIR)
    os.makedirs(aot_dir, exist_ok=True)

    def fn(*inputs):
        if matmul_precision is not None:
            with jax.default_matmul_precision(matmul_precision):
                return apply_fn(params, *inputs)
        return apply_fn(params, *inputs)

    platforms = list(platforms) if platforms else ["cpu", "tpu"]
    in_meta = list(signature["inputs"].items())
    written = []
    for bs in sorted(set(int(b) for b in batch_sizes)):
        args = [jnp.zeros((bs,) + tuple(int(d) for d in (meta.get("shape") or ())),
                          dtype=meta.get("dtype") or "float32")
                for _, meta in in_meta]
        for platform in platforms:
            exported = jexp.export(jax.jit(fn), platforms=[platform])(*args)
            base = os.path.join(aot_dir, f"model_b{bs}.{platform}")
            with open(base + ".jexport", "wb") as f:
                f.write(exported.serialize())
            with open(base + ".stablehlo.mlir", "w") as f:
                f.write(exported.mlir_module())
        written.append(bs)

    from jax._src import compiler

    opts = compiler.get_compile_options(num_replicas=1, num_partitions=1)
    with open(os.path.join(aot_dir, "compile_options.pb"), "wb") as f:
        f.write(opts.SerializeAsString())

    spec = {
        "batch_sizes": written,
        "inputs": [{"name": n, "shape": list(m.get("shape") or ()),
                    "dtype": m.get("dtype") or "float32"} for n, m in in_meta],
        "outputs": signature.get("outputs", ["output"]),
        "platforms": platforms,
    }
    with open(os.path.join(aot_dir, SPEC_FILE), "w") as f:
        json.dump(spec, f, indent=2)
    logger.info("AOT-exported batch sizes %s to %s", written, aot_dir)
    return aot_dir


def has_aot(export_dir):
    return os.path.exists(os.path.join(export_dir, AOT_DIR, SPEC_FILE))


def read_spec(export_dir):
    with open(os.path.join(export_dir, AOT_DIR, SPEC_FILE)) as f:
        return json.load(f)


def _pick_batch_size(spec, requested=None):
    sizes = sorted(spec["batch_sizes"])
    if requested is None:
        return sizes[-1]
    for b in sizes:
        if b >= requested:
            return b
    return sizes[-1]


# --------------------------------------------------------------------------
# Native runner (ctypes over native/pjrt_runner.cc)
# --------------------------------------------------------------------------

class _TosBuffer(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p),
                ("size_bytes", ctypes.c_longlong),
                ("dtype", ctypes.c_int),
                ("ndims", ctypes.c_int),
                ("dims", ctypes.c_longlong * 8)]


_runner_lib = None


def _load_runner_lib():
    global _runner_lib
    if _runner_lib is not None:
        return _runner_lib
    so = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "native", "libtos_pjrt.so")
    if not os.path.exists(so):
        raise FileNotFoundError(
            f"{so} not built; run `make -C native` (needs the PJRT C API "
            "header from the tensorflow wheel)")
    lib = ctypes.CDLL(so)
    lib.tos_runner_create.restype = ctypes.c_void_p
    lib.tos_runner_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_int]
    try:
        lib.tos_runner_create_opts.restype = ctypes.c_void_p
        lib.tos_runner_create_opts.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int]
        lib.tos_has_create_opts = True
    except AttributeError:
        # a libtos_pjrt.so built before the create-options extension:
        # still fully usable for optionless plugins (libtpu, the mock)
        lib.tos_has_create_opts = False
    lib.tos_runner_destroy.argtypes = [ctypes.c_void_p]
    lib.tos_runner_device_count.argtypes = [ctypes.c_void_p]
    lib.tos_runner_device_count.restype = ctypes.c_int
    lib.tos_runner_platform.argtypes = [ctypes.c_void_p]
    lib.tos_runner_platform.restype = ctypes.c_char_p
    lib.tos_runner_compile.restype = ctypes.c_void_p
    lib.tos_runner_compile.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char_p, ctypes.c_int]
    lib.tos_exec_destroy.argtypes = [ctypes.c_void_p]
    lib.tos_exec_num_outputs.argtypes = [ctypes.c_void_p]
    lib.tos_exec_num_outputs.restype = ctypes.c_int
    lib.tos_exec_run.restype = ctypes.c_int
    lib.tos_exec_run.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_TosBuffer), ctypes.c_int,
        ctypes.POINTER(_TosBuffer), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_int]
    lib.tos_free.argtypes = [ctypes.c_void_p]
    _runner_lib = lib
    return lib


def default_plugin_path():
    """The PJRT plugin to execute against: $TFOS_TPU_PJRT_PLUGIN, else
    libtpu from the installed wheel."""
    env = os.environ.get(PLUGIN_ENV)
    if env:
        return env
    try:
        import libtpu

        return os.path.join(os.path.dirname(libtpu.__file__), "libtpu.so")
    except ImportError:
        raise FileNotFoundError(
            f"no PJRT plugin: set {PLUGIN_ENV} or install libtpu")


class NativeRunner:
    """One PJRT client + one compiled executable (per process, like the
    reference's per-executor-JVM session singleton)."""

    def __init__(self, mlir_text, compile_options, plugin_path=None,
                 create_options=None):
        """``create_options`` ({key: str|int}) are forwarded to
        PJRT_Client_Create as NamedValues — libtpu needs none, but
        tunneled/proxying plugins reject an optionless create."""
        self._lib = _load_runner_lib()
        plugin = plugin_path or default_plugin_path()
        err = ctypes.create_string_buffer(4096)
        opts = dict(create_options or {})
        if not getattr(self._lib, "tos_has_create_opts", False):
            if opts:
                raise RuntimeError(
                    "this libtos_pjrt.so predates create-option support; "
                    "rebuild it (`make -C native`) to pass create_options")
            self._runner = self._lib.tos_runner_create(
                plugin.encode(), err, len(err))
        else:
            n = len(opts)
            keys = (ctypes.c_char_p * n)()
            svals = (ctypes.c_char_p * n)()
            ivals = (ctypes.c_longlong * n)()
            kinds = (ctypes.c_int * n)()
            for i, (key, val) in enumerate(opts.items()):
                keys[i] = str(key).encode()
                if isinstance(val, (int, bool)):     # bools ride as int64
                    kinds[i], ivals[i], svals[i] = 1, int(val), b""
                else:
                    kinds[i], svals[i] = 0, str(val).encode()
            self._runner = self._lib.tos_runner_create_opts(
                plugin.encode(), keys, svals, ivals, kinds, n, err,
                len(err))
        if not self._runner:
            raise RuntimeError(f"PJRT client init failed: {err.value.decode()}")
        mlir = mlir_text.encode() if isinstance(mlir_text, str) else mlir_text
        self._exec = self._lib.tos_runner_compile(
            self._runner, mlir, len(mlir), compile_options,
            len(compile_options), err, len(err))
        if not self._exec:
            self._lib.tos_runner_destroy(self._runner)
            self._runner = None
            raise RuntimeError(f"PJRT compile failed: {err.value.decode()}")

    @property
    def platform(self):
        return self._lib.tos_runner_platform(self._runner).decode()

    @property
    def num_outputs(self):
        return self._lib.tos_exec_num_outputs(self._exec)

    def run(self, arrays):
        """Execute one batch: list of numpy arrays -> list of numpy arrays."""
        import numpy as np

        ins = (_TosBuffer * len(arrays))()
        keepalive = []
        for i, a in enumerate(arrays):
            a = np.ascontiguousarray(a)
            keepalive.append(a)
            if a.dtype.name not in _PJRT_DTYPE:
                raise TypeError(f"unsupported dtype {a.dtype}")
            ins[i].data = a.ctypes.data_as(ctypes.c_void_p)
            ins[i].size_bytes = a.nbytes
            ins[i].dtype = _PJRT_DTYPE[a.dtype.name]
            ins[i].ndims = a.ndim
            for d, s in enumerate(a.shape):
                ins[i].dims[d] = s
        max_out = max(self.num_outputs, 1)
        outs = (_TosBuffer * max_out)()
        n_out = ctypes.c_int(0)
        err = ctypes.create_string_buffer(4096)
        rc = self._lib.tos_exec_run(self._exec, ins, len(arrays), outs,
                                    max_out, ctypes.byref(n_out), err, len(err))
        if rc != 0:
            raise RuntimeError(f"PJRT execute failed: {err.value.decode()}")
        results = []
        for i in range(n_out.value):
            o = outs[i]
            dtype = np.dtype("uint16" if o.dtype == 13 else  # bf16 via uint16
                             _PJRT_DTYPE_INV[o.dtype])
            shape = tuple(o.dims[d] for d in range(o.ndims))
            buf = ctypes.string_at(o.data, o.size_bytes)
            self._lib.tos_free(o.data)
            arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
            if o.dtype == 13:  # upcast bf16 -> float32 for the caller
                arr = (arr.astype(np.uint32) << 16).view(np.float32)
            results.append(arr)
        return results

    def close(self):
        if getattr(self, "_exec", None):
            self._lib.tos_exec_destroy(self._exec)
            self._exec = None
        if getattr(self, "_runner", None):
            self._lib.tos_runner_destroy(self._runner)
            self._runner = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# Unified loading
# --------------------------------------------------------------------------

def _platform_artifact(aot_dir, bs, ext, want):
    """Pick the artifact for `want` platform, falling back to any present."""
    path = os.path.join(aot_dir, f"model_b{bs}.{want}.{ext}")
    if os.path.exists(path):
        return path
    import glob as glob_mod

    candidates = sorted(glob_mod.glob(
        os.path.join(aot_dir, f"model_b{bs}.*.{ext}")))
    if not candidates:
        raise FileNotFoundError(
            f"no AOT artifact model_b{bs}.*.{ext} under {aot_dir}")
    logger.warning("no %s artifact for platform %r; using %s", ext, want,
                   os.path.basename(candidates[0]))
    return candidates[0]


def load_aot(export_dir, batch_size=None, engine="auto", plugin_path=None,
             platform=None, create_options=None):
    """Return ``(predict, spec, bs)``: a fixed-batch predict(arrays)->arrays
    callable for the chosen engine, the artifact spec, and the compiled
    batch size (callers pad/split with `predict_batched`).

    engine: 'native' (C++ PJRT runner), 'jax' (in-process deserialize+call),
    or 'auto' (native if the runner lib + a plugin are available).
    ``platform`` picks the per-platform artifact; defaults to 'tpu' for the
    native engine (libtpu) and the current jax backend for the jax engine.
    ``create_options`` ({key: str|int}) forward to PJRT_Client_Create for
    plugins that require them (see NativeRunner).
    """
    spec = read_spec(export_dir)
    bs = _pick_batch_size(spec, batch_size)
    aot_dir = os.path.join(export_dir, AOT_DIR)

    if engine == "auto":
        try:
            _load_runner_lib()
            plugin_path = plugin_path or default_plugin_path()
            engine = "native"
        except (FileNotFoundError, OSError) as e:
            logger.info("native runner unavailable (%s); using jax engine", e)
            engine = "jax"

    if engine == "native":
        # libtpu serves the tpu-lowered artifact; any other plugin (a CPU
        # PJRT plugin, the test mock) gets the cpu lowering — tpu custom
        # calls would not compile there
        want = platform or ("tpu" if "libtpu" in (plugin_path or "") else "cpu")
        with open(_platform_artifact(aot_dir, bs, "stablehlo.mlir", want)) as f:
            mlir = f.read()
        with open(os.path.join(aot_dir, "compile_options.pb"), "rb") as f:
            copts = f.read()
        runner = NativeRunner(mlir, copts, plugin_path,
                              create_options=create_options)
        logger.info("native PJRT runner on platform %r (batch=%d)",
                    runner.platform, bs)

        def predict(arrays):
            return runner.run(arrays)

        predict.runner = runner
        return predict, spec, bs

    import jax
    from jax import export as jexp

    want = platform or jax.default_backend()
    with open(_platform_artifact(aot_dir, bs, "jexport", want), "rb") as f:
        exported = jexp.deserialize(f.read())

    def predict(arrays):
        out = exported.call(*arrays)
        return list(out) if isinstance(out, (tuple, list)) else [out]

    return predict, spec, bs


def predict_batched(predict, arrays, compiled_bs):
    """Run a variable-size batch through a fixed-batch predict by splitting
    into compiled_bs chunks and repeat-padding the tail (trimmed after)."""
    import numpy as np

    n = int(arrays[0].shape[0])
    outs_accum = None
    for start in range(0, n, compiled_bs):
        chunk = [a[start:start + compiled_bs] for a in arrays]
        got = chunk[0].shape[0]
        if got < compiled_bs:
            pad = compiled_bs - got
            chunk = [np.concatenate([c] + [c[-1:]] * pad, axis=0) for c in chunk]
        outs = predict(chunk)
        outs = [np.asarray(o)[:got] for o in outs]
        if outs_accum is None:
            outs_accum = [[o] for o in outs]
        else:
            for acc, o in zip(outs_accum, outs):
                acc.append(o)
    if outs_accum is None:
        return []
    return [np.concatenate(acc, axis=0) for acc in outs_accum]
