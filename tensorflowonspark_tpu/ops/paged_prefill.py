"""Pallas paged-prefill flash attention: in-place page writes, O(chunk).

The blend write path (models/transformer._paged_attention_body) routes a
prefill chunk's k/v into the paged pool with one-hot einsums
(``bsn,bso,bshd->nohd``) over ALL ``kv_pages x page`` positions, then
reads attention context by gathering each row's FULL logical
``[max_seq, n_kv, Dh]`` view out of the pool — per chunk that is
O(pool) write traffic and O(max_seq) read traffic no matter how short
the chunk is.  Prefill-role replicas and the host-tier warm-miss path
live in this loop, so it sets ttft_ms directly.

This module is the prefill twin of ops/paged_attention.py (the PR-4
flash-decode read) and closes ROADMAP open item 1 with two kernels:

- a PAGE-WRITE kernel: the page table and per-row start offsets are
  scalar-prefetched, each grid step DMAs exactly one physical pool page
  to VMEM, blends the chunk positions that land in it (one-hot matmul,
  the same routing rule as the einsum blend — including the
  clip-at-last-block behaviour of bucket-pad overshoot), and stores the
  page back through ``input_output_aliases`` — per-chunk write bytes
  scale with ceil(S/page)+1 pages, not with the pool;
- a chunked flash-attention READ kernel: online softmax over
  [earlier context pages || current chunk] — context pages stream
  straight out of the pool (clamped index_map + ``pl.when``, only
  occupied pages visited, ops/paged_attention.py discipline), the
  chunk's own k/v come from the activations, and the causal
  ``j <= start + s`` rule splits into "all context visible" + an
  in-chunk triangle.  No dense ``[B, max_seq]`` kv view ever exists.

int8 pools: the chunk is quantized ONCE (bit-identical to
models/transformer._kv_quantize — deterministic f32 round/clip, so the
pool bytes match the blend exactly) and the payload + scale-page writes
ride the same in-place page store; the read kernel dequantizes context
pages inside the page read like the decode kernel.  Scale pools keep
their canonical ``[kv_pages, page, n_kv]`` layout on the write side (it
is the cache schema and the kv-migration wire format); the read side
uses the transposed-scales copy trick from ops/paged_attention.py.

Sink-page contract (serve.ContinuousBatcher): page-table entries past a
row's allocation and the whole table of a pad row alias a reserved
garbage sink page.  The write kernel honours it by construction — it
routes through the table like the blend, so pad rows and bucket-pad
overshoot land in the sink; concurrent sink stores from different rows
may race on TPU (the blend sums them instead) but sink bytes are
garbage by contract and masked on every read.

``interpret=`` threads through ops.default_interpret(), so CPU tier-1
executes these exact kernel bodies in the Pallas interpreter.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlibs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30  # large-finite: exp(NEG_INF - m) == 0 without inf-inf NaNs
_LANES = 128     # m/l carry a lane-replicated trailing dim for layout


def paged_prefill_available():
    """True when the TPU pallas extension (scalar prefetch) imported —
    callers fall back to the blend write + gather read otherwise."""
    return pltpu is not None


def _scratch(shape, dtype=jnp.float32):
    if _VMEM is not None:
        return pltpu.VMEM(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype)  # pragma: no cover


def _quantize(x):
    """Symmetric per-(token, head) int8 over head_dim.  MUST stay
    bit-identical to models/transformer._kv_quantize (deterministic f32
    round/clip): the kernel path requantizes the chunk itself, and pool
    bytes only match the blend reference because both quantizers agree.
    Duplicated here so ops never imports models (import cycle)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q8 = jnp.clip(jnp.round(xf / scale[..., None]), -127,
                  127).astype(jnp.int8)
    return q8, scale


def _dequantize(q8, scale, dtype):
    return (q8.astype(jnp.float32) * scale[..., None]).astype(dtype)


# ------------------------------------------------------------ write -----


def _page_write_kernel(table_ref, starts_ref, k_ref, v_ref, *rest,
                       page, s_chunk, max_pages, quant):
    """Grid (B, W): step (b, w) owns logical block start//page + w of
    row b and stores the chunk positions routed to it into the block's
    physical page (brought in by the index_map)."""
    if quant:
        ks_ref, vs_ref = rest[:2]
        pk_in, pv_in, pks_in, pvs_in = rest[2:6]
        pk_out, pv_out, pks_out, pvs_out = rest[6:]
    else:
        pk_in, pv_in, pk_out, pv_out = rest
    b = pl.program_id(0)
    w = pl.program_id(1)
    start = starts_ref[b]
    lb = start // page + w

    # blocks past the table are CLAMPED by the index_map onto the
    # previous step's page, whose out-block VMEM buffer is retained
    # (same index -> no flush/refetch): a skipped step must not touch
    # out_ref or it would overwrite the predecessor's stores with the
    # stale pre-write in_ref content
    @pl.when(lb < max_pages)
    def _store():
        # hit[p, s]: the blend routes chunk position s to offset p of
        # THIS block — same rule as the einsum write, including the
        # clip(pos//page, 0, max_pages-1) that parks bucket-pad
        # overshoot in the last logical block (the sink, by contract)
        pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (page, s_chunk), 1)
        blk = jnp.clip(pos // page, 0, max_pages - 1)
        offs = jax.lax.broadcasted_iota(jnp.int32, (page, s_chunk), 0)
        hit = (blk == lb) & ((pos % page) == offs)
        oh = hit.astype(jnp.float32)                 # [page, S]
        row = jnp.any(hit, axis=1)[:, None, None]    # [page, 1, 1]

        def _blend(chunk_ref, in_ref, out_ref):
            # one-hot matmul = the dynamic shift start%page (and, like
            # the einsum, a SUM where clipped positions collide); f32
            # accumulation is exact for the one-term rows
            x = chunk_ref[0].astype(jnp.float32)     # [S, n_kv, Dh]
            n_kv, dh = x.shape[1], x.shape[2]
            new = jax.lax.dot_general(
                oh, x.reshape(s_chunk, n_kv * dh),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            cur = in_ref[0]                          # [page, n_kv, Dh]
            out_ref[0] = jnp.where(
                row, new.reshape(page, n_kv, dh).astype(cur.dtype), cur)

        _blend(k_ref, pk_in, pk_out)
        _blend(v_ref, pv_in, pv_out)
        if quant:

            def _blend_scale(sc_ref, in_ref, out_ref):
                new = jax.lax.dot_general(
                    oh, sc_ref[0], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [page, n_kv]
                out_ref[0] = jnp.where(row[:, :, 0], new, in_ref[0])

            _blend_scale(ks_ref, pks_in, pks_out)
            _blend_scale(vs_ref, pvs_in, pvs_out)


def _write_pages(k_st, v_st, k_sc, v_sc, pages_key, pages_value,
                 key_scales, value_scales, table, starts, *, interpret):
    """In-place page store: returns the updated pool leaves (inputs are
    aliased to outputs, so under jit the pool never copies)."""
    B, S, n_kv, Dh = k_st.shape
    NP, page = pages_key.shape[:2]
    max_pages = table.shape[1]
    quant = k_sc is not None
    # a chunk touches at most ceil(S/page)+1 logical blocks (the +1 is
    # the straddle of an unaligned start)
    W = -(-S // page) + 1

    def _block(b, w, table_ref, starts_ref):
        lb = starts_ref[b] // page + w
        return table_ref[b, jnp.minimum(lb, max_pages - 1)]

    chunk_spec = pl.BlockSpec((1, S, n_kv, Dh),
                              lambda b, w, tr, sr: (b, 0, 0, 0))
    pool_spec = pl.BlockSpec(
        (1, page, n_kv, Dh),
        lambda b, w, tr, sr: (_block(b, w, tr, sr), 0, 0, 0))
    in_specs = [chunk_spec, chunk_spec]
    inputs = [k_st, v_st]
    out_specs = [pool_spec, pool_spec]
    out_shape = [jax.ShapeDtypeStruct(pages_key.shape, pages_key.dtype),
                 jax.ShapeDtypeStruct(pages_value.shape,
                                      pages_value.dtype)]
    if quant:
        csc_spec = pl.BlockSpec((1, S, n_kv),
                                lambda b, w, tr, sr: (b, 0, 0))
        # scale pools stay in their canonical [NP, page, n_kv] layout:
        # this is the cache schema and the kv-migration wire format, and
        # the blocks are tiny (4/Dh of the payload bytes)
        psc_spec = pl.BlockSpec(
            (1, page, n_kv),
            lambda b, w, tr, sr: (_block(b, w, tr, sr), 0, 0))
        in_specs += [csc_spec, csc_spec]
        inputs += [k_sc, v_sc]
        out_specs += [psc_spec, psc_spec]
        out_shape += [
            jax.ShapeDtypeStruct(key_scales.shape, key_scales.dtype),
            jax.ShapeDtypeStruct(value_scales.shape, value_scales.dtype)]
    pool_inputs = [pages_key, pages_value]
    pool_in_specs = [pool_spec, pool_spec]
    if quant:
        pool_inputs += [key_scales, value_scales]
        pool_in_specs += [psc_spec, psc_spec]
    # input_output_aliases indices COUNT the scalar-prefetch operands
    # (table, starts), then chunk payloads (+ chunk scales), then pools
    first_pool = 2 + len(inputs)
    aliases = {first_pool + i: i for i in range(len(pool_inputs))}

    kernel = functools.partial(
        _page_write_kernel, page=page, s_chunk=S, max_pages=max_pages,
        quant=quant)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, W),
        in_specs=in_specs + pool_in_specs,
        out_specs=out_specs)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(table, starts, *inputs, *pool_inputs)


# ------------------------------------------------------------- read -----


def _prefill_read_kernel(table_ref, starts_ref, q_ref, ck_ref, cv_ref,
                         pk_ref, pv_ref, *rest, sm_scale, page, s_chunk,
                         group, n_ctx, quant):
    """Grid (B, n_kv, n_ctx + 1): j < n_ctx walks row b's occupied
    context pages, j == n_ctx folds in the chunk's own k/v and
    normalizes — one online softmax over [context || chunk]."""
    if quant:
        ks_ref, vs_ref = rest[:2]
        rest = rest[2:]
    out_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    start = starts_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    def _online(k, v, kmask):
        q = q_ref[0, 0].astype(jnp.float32)          # [ROWS, Dh]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = jnp.where(kmask, s * sm_scale, NEG_INF)
        m_prev = m_scr[:, :1]                        # [ROWS, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # context pages: every chunk query sits at or past `start`, so the
    # causal rule degenerates to "positions < start are visible" — the
    # straddled page's fresh chunk positions (>= start) are masked off
    # here and come from the activations below instead.  Pages at or
    # past start skip compute (their DMA was clamped onto the last
    # occupied page by the index_map, which pallas elides as a re-fetch)
    @pl.when((j < n_ctx) & (j * page < start))
    def _ctx():
        k = pk_ref[0, :, 0, :].astype(jnp.float32)   # [page, Dh]
        v = pv_ref[0, :, 0, :].astype(jnp.float32)
        if quant:
            # int8 dequant fused into the page read, decode-kernel style
            k = k * ks_ref[0, 0][:, None]
            v = v * vs_ref[0, 0][:, None]
        k_pos = j * page + jax.lax.broadcasted_iota(
            jnp.int32, (1, page), 1)
        _online(k, v, k_pos < start)

    # the chunk itself: row r of the grouped q block is (query position
    # r//group, GQA member r%group); chunk key jc is visible iff
    # jc <= r//group (the j <= start + s rule with both sides >= start)
    @pl.when(j == n_ctx)
    def _chunk():
        k = ck_ref[0, :, 0, :].astype(jnp.float32)   # [S, Dh]
        v = cv_ref[0, :, 0, :].astype(jnp.float32)
        rows = out_ref.shape[2]
        jc = jax.lax.broadcasted_iota(jnp.int32, (rows, s_chunk), 1)
        qs = jax.lax.broadcasted_iota(jnp.int32, (rows, s_chunk), 0)
        _online(k, v, jc <= qs // group)
        # every query sees at least its own position, so l > 0 for all
        # live rows; the guard only shields the ROWS padding
        out_ref[0, 0] = acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)


def _read_attention(q, ck, cv, pages_key, pages_value, key_scales,
                    value_scales, table, starts, *, sm_scale, interpret):
    """Flash attention of the chunk against [context pages || chunk]."""
    B, S, H, Dh = q.shape
    NP, page, n_kv = pages_key.shape[:3]
    max_pages = table.shape[1]
    quant = key_scales is not None
    group = H // n_kv
    rows = S * group
    # grouped-q rows pad to the sublane tile of q's dtype
    mult = 8 if q.dtype == jnp.float32 else 16
    ROWS = max(mult, -(-rows // mult) * mult)
    q_r = q.reshape(B, S, n_kv, group, Dh).transpose(0, 2, 1, 3, 4)
    q_r = q_r.reshape(B, n_kv, rows, Dh)
    if ROWS != rows:
        q_r = jnp.pad(q_r, ((0, 0), (0, 0), (0, ROWS - rows), (0, 0)))

    def _ctx_page(b, h, j, table_ref, starts_ref):
        # clamp at the last occupied context page so steps past the
        # context re-name the previous block (pallas elides the re-fetch)
        last = jnp.maximum(starts_ref[b] - 1, 0) // page
        return table_ref[b, jnp.minimum(j, last)]

    q_spec = pl.BlockSpec((1, 1, ROWS, Dh),
                          lambda b, h, j, tr, sr: (b, h, 0, 0))
    chunk_spec = pl.BlockSpec((1, S, 1, Dh),
                              lambda b, h, j, tr, sr: (b, 0, h, 0))
    kv_spec = pl.BlockSpec(
        (1, page, 1, Dh),
        lambda b, h, j, tr, sr: (_ctx_page(b, h, j, tr, sr), 0, h, 0))
    out_spec = pl.BlockSpec((1, 1, ROWS, Dh),
                            lambda b, h, j, tr, sr: (b, h, 0, 0))
    in_specs = [q_spec, chunk_spec, chunk_spec, kv_spec, kv_spec]
    inputs = [q_r, ck, cv, pages_key, pages_value]
    if quant:
        # minor-dim = page axis so the scale blocks are lane-tiled; this
        # copies the (small) scale arrays only, never the payload pool
        sc_spec = pl.BlockSpec(
            (1, 1, page),
            lambda b, h, j, tr, sr: (_ctx_page(b, h, j, tr, sr), h, 0))
        in_specs += [sc_spec, sc_spec]
        inputs += [key_scales.transpose(0, 2, 1),
                   value_scales.transpose(0, 2, 1)]

    kernel = functools.partial(
        _prefill_read_kernel, sm_scale=float(sm_scale), page=page,
        s_chunk=S, group=group, n_ctx=max_pages, quant=quant)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_kv, max_pages + 1),
        in_specs=in_specs,
        out_specs=[out_spec],
        scratch_shapes=[
            _scratch((ROWS, _LANES)),
            _scratch((ROWS, _LANES)),
            _scratch((ROWS, Dh)),
        ])
    (out,) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((B, n_kv, ROWS, Dh),
                                        jnp.float32)],
        interpret=interpret,
    )(table, starts, *inputs)
    out = out[:, :, :rows].reshape(B, n_kv, S, group, Dh)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, S, H, Dh).astype(q.dtype)


# ---------------------------------------------------------- wrapper -----


def paged_prefill(q, k, v, pages_key, pages_value, page_table, starts, *,
                  key_scales=None, value_scales=None, sm_scale=None,
                  interpret=None):
    """Chunked prefill over an in-place paged kv pool: page-granular
    writes, then flash attention over [context pages || chunk].

    Args:
      q, k, v: ``[B, S, *, Dh]`` chunk activations (q has H heads, k/v
        the narrow n_kv) — the PR-5 batched ragged prefill layout, one
        row per admitted request (pad rows carry a sink page table).
      pages_key / pages_value: the pool, ``[kv_pages, page, n_kv, Dh]``
        — activation dtype, or int8 with ``key_scales``/``value_scales``
        ``[kv_pages, page, n_kv]`` f32 (the chunk is requantized here,
        bit-identical to the blend's storage).
      page_table: ``[B, max_pages]`` int32; entries past a row's
        allocation MUST alias the caller's sink page (they do receive
        bucket-pad overshoot writes).
      starts: ``[B]`` int32 pre-write positions (the row's cache_index
        before this chunk): chunk position s lands at ``starts + s`` and
        sees keys ``j <= starts + s``.

    Returns ``(out, pools)``: ``out [B, S, H, Dh]`` in q's dtype, and
    ``pools = (pages_key, pages_value, key_scales, value_scales)`` — the
    updated pool leaves (inputs are aliased to outputs so the pool
    updates in place under jit; scale leaves are None without int8).
    """
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "paged_prefill needs jax.experimental.pallas.tpu (scalar "
            "prefetch); use the blend write path "
            "(TransformerConfig.paged_prefill_impl='blend') instead")
    B, S, H, Dh = q.shape
    NP, page, n_kv, Dh_kv = pages_key.shape
    if pages_value.shape != pages_key.shape or Dh_kv != Dh:
        raise ValueError(
            f"pool shapes {pages_key.shape} / {pages_value.shape} must "
            f"match and end in head_dim {Dh}")
    if k.shape != (B, S, n_kv, Dh) or v.shape != k.shape:
        raise ValueError(
            f"chunk k/v {k.shape} / {v.shape} must be "
            f"{(B, S, n_kv, Dh)}")
    if H % n_kv:
        raise ValueError(
            f"q heads {H} must be a multiple of kv heads {n_kv} (GQA "
            "groups map onto their kv head inside the kernel)")
    quant = pages_key.dtype == jnp.int8
    if quant and (key_scales is None or value_scales is None):
        raise ValueError("int8 pools need key_scales and value_scales "
                         "[kv_pages, page, n_kv]")
    if not quant and (key_scales is not None or value_scales is not None):
        raise ValueError("scales are only meaningful for int8 pools")
    if sm_scale is None:
        sm_scale = 1.0 / (Dh ** 0.5)
    if interpret is None:
        from tensorflowonspark_tpu.ops import default_interpret
        interpret = default_interpret()
    table = page_table.astype(jnp.int32)
    starts = starts.astype(jnp.int32)

    if quant:
        k_st, k_sc = _quantize(k)
        v_st, v_sc = _quantize(v)
        # the read side sees exactly what a pool round-trip would give
        # (quantization is deterministic, so this matches the blend
        # reference bit for bit)
        ck = _dequantize(k_st, k_sc, k.dtype)
        cv = _dequantize(v_st, v_sc, v.dtype)
    else:
        k_st, v_st, k_sc, v_sc = k, v, None, None
        ck, cv = k, v

    pools = _write_pages(k_st, v_st, k_sc, v_sc, pages_key, pages_value,
                         key_scales, value_scales, table, starts,
                         interpret=interpret)
    new_pk, new_pv = pools[0], pools[1]
    new_ks = pools[2] if quant else None
    new_vs = pools[3] if quant else None
    # the read walks the POST-write pool: context pages are byte-equal
    # either way, and the straddled page's fresh positions are masked
    out = _read_attention(q, ck, cv, new_pk, new_pv, new_ks, new_vs,
                          table, starts, sm_scale=sm_scale,
                          interpret=interpret)
    return out, (new_pk, new_pv, new_ks, new_vs)
