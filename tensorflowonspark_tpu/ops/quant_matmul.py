"""Pallas fused-dequant weight matmuls (W8A16 / W4A16 decode path).

Decode is HBM-bandwidth-bound: every generated token reads every weight
once.  The materialized path (`quantize.dequantize_tree`) hopes XLA
fuses ``q.astype(dtype) * scale`` into the consuming matmul's operand
read — these kernels make the guarantee structural instead.  Each is a
weight-stationary blocked matmul whose weight operand arrives in its
QUANTIZED storage form; the dense bf16/f32 kernel never exists in HBM:

- ``_int8_kernel``: weight tiles stream as int8 ``[bk, bn]`` blocks with
  a per-output-channel f32 scale row ``[1, bn]``; the tile dequantizes
  in VMEM (``q.astype(f32) * scale``, cast to the activation dtype) and
  feeds the MXU with f32 accumulation across the k grid.  1/4 the
  weight bytes of f32 per token (1/2 of bf16), plus 4 bytes per output
  channel of scale.
- ``_int4_kernel``: weights stream NIBBLE-PACKED (two signed 4-bit rows
  per int8 byte along the input dim — ``quantize.int4_pack``'s layout)
  with per-``group_size`` AWQ-style scales.  Sign-extension is two
  int32 shifts per nibble, done after the VMEM load; the packed byte
  rows never unpack in HBM.  The activation is split OUTSIDE the kernel
  into even/odd input-row planes (``x[:, 0::2]`` / ``x[:, 1::2]``), so
  a packed row ``i`` multiplies plane columns ``i`` directly —
  ``y = sum_g xe_g @ (lo_g * s_g) + xo_g @ (hi_g * s_g)`` — and no
  in-kernel row interleave (an awkward sublane shuffle) is needed.
  1/8 the weight bytes of f32, plus 4 bytes per (group, channel).

Both kernels zero-pad M/K/N up to their block grid outside the call and
slice the result, so any shapes are correct; block shapes are built
from runtime variables and respect the TPU tile grid (lane dim
multiples of 128, sublane multiples of 8 f32 / 16 bf16; the packed int4
lane dim covers two logical input rows per byte — see
``analysis/pallas_tiles`` for the corresponding scan carve-out).
``interpret=`` threads through ``ops.default_interpret()`` so the CPU
tier executes these exact kernel bodies in the Pallas interpreter, and
``quant_matmul_reference`` is the gather/einsum oracle with identical
dequant semantics for the parity tests.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlibs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_LANE = 128


def quant_matmul_available():
    """True when the TPU pallas extension imported — QuantDense falls
    back to the inline-dequant einsum path otherwise."""
    return pltpu is not None


def _scratch(shape, dtype=jnp.float32):
    if _VMEM is not None:
        return pltpu.VMEM(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype)  # pragma: no cover


def _round_up(x, mult):
    return -(-int(x) // mult) * mult


def _sublane(dtype):
    return 8 if dtype == jnp.float32 else 16


def _pad2(a, rows, cols):
    pr, pc = rows - a.shape[0], cols - a.shape[1]
    if pr or pc:
        a = jnp.pad(a, ((0, pr), (0, pc)))
    return a


def _int8_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    # dequant in VMEM: int8 tile * per-channel scale, cast to the
    # activation dtype so the MXU sees the same operands the
    # materialized dequantize_tree path feeds it
    w = (q_ref[...].astype(jnp.float32) * s_ref[...]).astype(x_ref.dtype)
    acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
        x_ref[...], w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _finish():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _int4_kernel(xe_ref, xo_ref, p_ref, s_ref, o_ref, acc_ref, *,
                 n_k, gpt, gh):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    # sign-extend both nibbles of every packed byte: arithmetic shifts
    # in int32 (low nibble = bits 0-3, high = bits 4-7); packed row i
    # holds logical input rows 2i (lo) and 2i+1 (hi), which line up
    # with the even/odd activation planes
    pi = p_ref[...].astype(jnp.int32)
    lo = ((pi << 28) >> 28).astype(jnp.float32)
    hi = ((pi << 24) >> 28).astype(jnp.float32)
    acc = acc_ref[...]
    for g in range(gpt):              # static: scale groups per k-tile
        rows = slice(g * gh, (g + 1) * gh)
        s = s_ref[g:g + 1, :]
        wl = (lo[rows] * s).astype(xe_ref.dtype)
        wh = (hi[rows] * s).astype(xe_ref.dtype)
        acc = acc + jax.lax.dot_general(
            xe_ref[:, rows], wl, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc = acc + jax.lax.dot_general(
            xo_ref[:, rows], wh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    acc_ref[...] = acc

    @pl.when(k == n_k - 1)
    def _finish():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _int8_call(x2, q, scale, block_m, block_n, block_k, interpret):
    M, K = x2.shape
    _, N = q.shape
    scale = jnp.asarray(scale, jnp.float32).reshape(1, N)
    sub = _sublane(x2.dtype)
    bm = _round_up(min(block_m, _round_up(M, sub)), sub)
    bk = min(block_k, _round_up(K, _LANE))
    bn = min(block_n, _round_up(N, _LANE))
    Mp, Kp, Np = _round_up(M, bm), _round_up(K, bk), _round_up(N, bn)
    nm, nn, nk = Mp // bm, Np // bn, Kp // bk
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            pl.BlockSpec((bk, bn), lambda m, n, k: (k, n)),
            pl.BlockSpec((1, bn), lambda m, n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
        scratch_shapes=[_scratch((bm, bn))])
    out = pl.pallas_call(
        functools.partial(_int8_kernel, n_k=nk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Mp, Np), x2.dtype),
        interpret=interpret,
    )(_pad2(x2, Mp, Kp), _pad2(q, Kp, Np), _pad2(scale, 1, Np))
    return out[:M, :N]


def _int4_call(x2, w, block_m, block_n, interpret):
    M, _ = x2.shape
    p = w.q
    scale = jnp.asarray(w.scale, jnp.float32)
    Kp2, N = p.shape
    gh = w.group_size // 2            # packed rows per scale group
    if _LANE % gh == 0:
        bkp = _LANE                   # whole groups tile the 128 lanes
    elif gh % _LANE == 0:
        bkp = gh                      # one big group spans whole tiles
    else:
        raise ValueError(
            f"group_size {w.group_size} does not tile the {_LANE}-wide "
            f"lane grid: half-group {gh} must divide {_LANE} or be a "
            f"multiple of it")
    gpt = bkp // gh                   # scale groups per k-tile
    sub = _sublane(x2.dtype)
    bm = _round_up(min(block_m, _round_up(M, sub)), sub)
    bn = min(block_n, _round_up(N, _LANE))
    Mp = _round_up(M, bm)
    Kp2p = _round_up(Kp2, bkp)
    Np = _round_up(N, bn)
    nm, nn, nk = Mp // bm, Np // bn, Kp2p // bkp
    # split the activation into even/odd input-row planes so plane
    # column i multiplies packed row i's lo/hi nibble respectively
    x2 = _pad2(x2, Mp, 2 * Kp2p)
    xe, xo = x2[:, 0::2], x2[:, 1::2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bkp), lambda m, n, k: (m, k)),
            pl.BlockSpec((bm, bkp), lambda m, n, k: (m, k)),
            pl.BlockSpec((bkp, bn), lambda m, n, k: (k, n)),
            pl.BlockSpec((gpt, bn), lambda m, n, k: (k, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
        scratch_shapes=[_scratch((bm, bn))])
    out = pl.pallas_call(
        functools.partial(_int4_kernel, n_k=nk, gpt=gpt, gh=gh),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Mp, Np), x2.dtype),
        interpret=interpret,
    )(xe, xo, _pad2(p, Kp2p, Np), _pad2(scale, Kp2p // gh, Np))
    return out[:M, :N]


def quant_matmul(x, w, *, block_m=128, block_n=128, block_k=512,
                 interpret=None):
    """``x @ dequant(w)`` with the dequant fused into the weight read.

    Args:
      x: ``[..., K]`` floating activations (any leading batch shape).
      w: a quantized kernel leaf — the int8 ``{"q": [K, N] int8,
        "scale": [1, N] f32}`` dict ``quantize.quantize_tree`` emits, or
        a nibble-packed ``quantize.Int4Weight``.
      block_m / block_n / block_k: tile sizes (n/k must be multiples of
        128; clamped down for small operands).  ``block_k`` applies to
        the int8 kernel only — the int4 k-tile is derived from the
        group size.

    Returns ``[..., N]`` in x's dtype (f32-accumulated).
    """
    from tensorflowonspark_tpu import quantize

    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "quant_matmul needs jax.experimental.pallas.tpu; use the "
            "inline dequantize path "
            "(TransformerConfig.quant_matmul_impl='dequant') instead")
    if interpret is None:
        from tensorflowonspark_tpu.ops import default_interpret
        interpret = default_interpret()
    if block_n % _LANE or block_k % _LANE:
        raise ValueError(f"block_n/block_k must be multiples of {_LANE}, "
                         f"got {block_n}/{block_k}")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        raise ValueError(f"activations must be floating, got {x.dtype}")

    if isinstance(w, quantize.Int4Weight):
        K, N = w.in_dim, w.out_dim
    elif quantize._is_qleaf(w):
        if w["q"].ndim != 2:
            raise ValueError(f"quant_matmul needs a 2-D [in, out] kernel, "
                             f"got {w['q'].shape}")
        K, N = w["q"].shape
    else:
        raise TypeError(
            f"w must be an int8 quantized-leaf dict or Int4Weight, "
            f"got {type(w)!r}")
    *batch, Kx = x.shape
    if Kx != K:
        raise ValueError(f"activation K {Kx} != weight in_dim {K}")
    M = 1
    for d in batch:
        M *= int(d)
    x2 = x.reshape(M, K)
    if isinstance(w, quantize.Int4Weight):
        out = _int4_call(x2, w, block_m, block_n, interpret)
    else:
        out = _int8_call(x2, w["q"], w["scale"], block_m, block_n,
                         block_k, interpret)
    return out.reshape(*batch, N)


def quant_matmul_reference(x, w):
    """Gather/einsum oracle with the kernel's exact dequant semantics
    (f32 dequant -> cast to the activation dtype -> f32-accumulated
    matmul -> cast back) — the parity-test baseline, and numerically the
    materialized ``dequantize_tree`` + Dense path."""
    from tensorflowonspark_tpu import quantize

    wf = quantize.dequantize_leaf(w).astype(x.dtype)
    out = jnp.einsum("...k,kn->...n", x, wf,
                     preferred_element_type=jnp.float32)
    return out.astype(x.dtype)
