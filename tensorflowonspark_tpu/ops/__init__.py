"""TPU kernel ops (Pallas).

The reference delegates all tensor math to TensorFlow and ships no kernels
of its own (SURVEY.md §1 "delegates all actual tensor math ... to TensorFlow
itself"); in a TPU-native framework the hot ops are first-class: hand-tiled
Pallas kernels that stream blocks HBM→VMEM and keep the MXU busy, with an
interpret-mode path so the same kernels are testable on the CPU mesh.

- flash_attention : blocked online-softmax attention, O(S) memory per core
- fused_layernorm : single-pass layernorm, f32 accumulation in VMEM
"""
from tensorflowonspark_tpu.ops.flash_attention import flash_attention
from tensorflowonspark_tpu.ops.layernorm import fused_layernorm

__all__ = ["flash_attention", "fused_layernorm"]


def default_interpret():
    """Pallas kernels run natively on TPU, in interpret mode elsewhere
    (the CPU test mesh), so one code path covers both."""
    import jax
    return jax.default_backend() != "tpu"
