"""TPU kernel ops (Pallas).

The reference delegates all tensor math to TensorFlow and ships no kernels
of its own (SURVEY.md §1 "delegates all actual tensor math ... to TensorFlow
itself"); in a TPU-native framework the hot ops are first-class: hand-tiled
Pallas kernels that stream blocks HBM→VMEM and keep the MXU busy, with an
interpret-mode path so the same kernels are testable on the CPU mesh.

- flash_attention : blocked online-softmax attention, O(S) memory per core
- fused_layernorm : single-pass layernorm, f32 accumulation in VMEM
- fused_unembed_xent : chunked lm_head matmul + cross entropy, no
  materialized logits (XLA scan, not Pallas — the MXU matmul is already
  optimal; the win is memory, see ops/xent.py)
- adamw_fused / lion_fused : single-pass optimizer updates — read
  grad/param/moments once, write param/moments once, clip scale inlined
  (see ops/fused_optim.py; surfaced via optim.make_optimizer)
- paged_attention : flash-decode over the paged serving kv pool — page
  table scalar-prefetched, only occupied pages read (in place, no
  logical-view gather), online softmax + split-K LSE combine, int8
  dequant fused into the page read (see ops/paged_attention.py;
  the default paged read path, TransformerConfig.paged_attn_impl)
- paged_prefill : chunked prefill over the same pool — the chunk's k/v
  store page-granular and IN PLACE (input_output_aliases, int8
  requantization fused into the page store), then one online softmax
  over [occupied context pages || chunk]; O(chunk) traffic, no dense
  [B, max_seq] kv view (see ops/paged_prefill.py; the default S>1
  paged path, TransformerConfig.paged_prefill_impl)
- quant_matmul : weight-stationary matmul over int8 / nibble-packed
  int4 kernels — weight tiles dequantize in VMEM (per-channel or
  per-group scales), the dense bf16/f32 kernel never exists in HBM
  (see ops/quant_matmul.py; the QuantDense decode path,
  TransformerConfig.quant_matmul_impl)
"""
from tensorflowonspark_tpu.ops.flash_attention import flash_attention
from tensorflowonspark_tpu.ops.fused_optim import adamw_fused, lion_fused
from tensorflowonspark_tpu.ops.layernorm import fused_layernorm
from tensorflowonspark_tpu.ops.paged_attention import paged_attention
from tensorflowonspark_tpu.ops.paged_prefill import paged_prefill
from tensorflowonspark_tpu.ops.quant_matmul import (quant_matmul,
                                                    quant_matmul_available)
from tensorflowonspark_tpu.ops.xent import fused_unembed_xent

__all__ = ["flash_attention", "fused_layernorm", "fused_unembed_xent",
           "adamw_fused", "lion_fused", "paged_attention",
           "paged_prefill", "quant_matmul", "quant_matmul_available"]


def default_interpret():
    """Pallas kernels run natively on TPU, in interpret mode elsewhere
    (the CPU test mesh), so one code path covers both."""
    import jax
    return jax.default_backend() != "tpu"
