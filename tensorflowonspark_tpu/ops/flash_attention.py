"""Pallas TPU flash attention.

Blocked attention with a numerically-stable online softmax: the [S, S]
score matrix never materializes in HBM.  The grid streams K/V blocks
through VMEM (innermost grid dim) while per-q-block running max /
denominator / accumulator live in VMEM scratch that persists across the
sequential k-steps of the TPU grid; both matmuls run on the MXU in f32
accumulation.  Causal q/k block pairs with no overlap are skipped entirely
(`pl.when`), halving the work for causal LMs.

Composes with ring attention (parallel/ring_attention.py): ring handles the
cross-device sequence axis, this kernel the on-device blocks.

Backward is a custom VJP that recomputes attention from the saved q/k/v
(residuals are O(B·S·H·D)) through the JAX reference implementation — note
the backward pass itself still materializes the [S, S] scores, so the
O(S)-memory claim holds for forward/serving; a blocked pallas backward is
the upgrade path for long-context training.

The reference framework has no kernels at all — math is delegated to TF
(SURVEY.md §1); this file is net-new TPU machinery.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlibs; interpret mode needs it not
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30  # large-finite: exp(NEG_INF - m) == 0 without inf-inf NaNs


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                sm_scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    def _block():
        q = q_ref[0, 0].astype(jnp.float32)          # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)          # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        # [bq, bk] scores on the MXU, f32 accumulation
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_len                        # padded keys
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                         # [bq, 1]
        l_prev = l_scr[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                        # [bq, bk]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip blocks strictly above the diagonal
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _block()
    else:
        _block()

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _pad_seq(x, block):
    s = x.shape[2]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x


def _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    # [B, S, H, D] (framework layout) -> [B, H, S, D]
    B, S, H, D = q.shape
    qt = _pad_seq(q.transpose(0, 2, 1, 3), block_q)
    kt = _pad_seq(k.transpose(0, 2, 1, 3), block_k)
    vt = _pad_seq(v.transpose(0, 2, 1, 3), block_k)
    Sq, Sk = qt.shape[2], kt.shape[2]
    nq, nk = Sq // block_q, Sk // block_k

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=S)
    kw = {}
    if _VMEM is not None:
        kw["scratch_shapes"] = [
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ]
    else:  # pragma: no cover - CPU-only jaxlib
        kw["scratch_shapes"] = [
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((block_q, D), jnp.float32),
        ]

    out = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
        **kw,
    )(qt, kt, vt)
    return out[:, :, :S].transpose(0, 2, 1, 3)


def attention_reference(q, k, v, causal=True, sm_scale=None):
    """Dense reference with semantics identical to the kernel (f32 softmax,
    large-finite mask).  Used for tests and as the recompute path in the
    custom VJP."""
    D = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Sq, Sk), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                           interpret)


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: attention_reference(q, k, v, causal, sm_scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=True, sm_scale=None,
                    block_q=512, block_k=512, interpret=None):
    """Flash attention over [B, S, H, D] q/k/v.

    Sequence lengths need not be multiples of the block sizes (padded keys
    are masked out).  `interpret=None` auto-selects: native Mosaic on TPU,
    interpreter elsewhere (the CPU test mesh).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        from tensorflowonspark_tpu.ops import default_interpret
        interpret = default_interpret()
    S = q.shape[1]
    block_q = min(block_q, max(S, 16))
    block_k = min(block_k, max(k.shape[1], 16))
    return _flash(q, k, v, causal, float(sm_scale), int(block_q),
                  int(block_k), bool(interpret))
