"""Pallas TPU flash attention (forward + blocked backward).

Blocked attention with a numerically-stable online softmax: the [S, S]
score matrix never materializes in HBM — in either direction.  The forward
grid streams K/V blocks through VMEM (innermost grid dim) while per-q-block
running max / denominator / accumulator live in VMEM scratch that persists
across the sequential k-steps of the TPU grid, and emits the per-row
logsumexp.  The backward recomputes probabilities blockwise from (q, k,
lse) — flash-style recompute, residuals O(B·S·H·D) — in two kernels: one
accumulating dq over streamed K/V blocks, one accumulating dk/dv over
streamed Q/dO blocks.  All matmuls run on the MXU with f32 accumulation.
Causal q/k block pairs with no overlap are skipped entirely (`pl.when`),
halving the work for causal LMs.

Composes with ring attention (parallel/ring_attention.py): ring handles the
cross-device sequence axis, this kernel the on-device blocks.

The reference framework has no kernels at all — math is delegated to TF
(SURVEY.md §1); this file is net-new TPU machinery.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlibs; interpret mode needs it not
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30  # large-finite: exp(NEG_INF - m) == 0 without inf-inf NaNs


def _scratch(shape, dtype=jnp.float32):
    if _VMEM is not None:
        return pltpu.VMEM(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype)  # pragma: no cover


def _block_mask(qi, ki, block_q, block_k, seq_len, causal):
    """[bq, bk] validity mask for one (q-block, k-block) tile: real rows,
    real keys, and the causal triangle."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = jnp.logical_and(q_pos < seq_len, k_pos < seq_len)
    if causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)
    return mask


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                sm_scale, causal, block_q, block_k, seq_len, need_lse):
    if need_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    def _block():
        q = q_ref[0, 0].astype(jnp.float32)          # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)          # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        # [bq, bk] scores on the MXU, f32 accumulation
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        s = jnp.where(_block_mask(qi, ki, block_q, block_k, seq_len, causal),
                      s, NEG_INF)

        m_prev = m_scr[:, :1]                         # [bq, 1]
        l_prev = l_scr[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                        # [bq, bk]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip blocks strictly above the diagonal
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _block()
    else:
        _block()

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        if need_lse:
            # lse rows that saw no valid key (padding) get a finite sentinel
            # so the backward's exp(NEG_INF - lse) underflows to exactly 0
            m = m_scr[:, :1]
            lse = jnp.where(m <= NEG_INF / 2, 0.0, m + jnp.log(l))
            lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref[0, 0].shape)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref,
                   dq_scr, *, sm_scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, dq_scr.dtype)

    def _block():
        q = q_ref[0, 0].astype(jnp.float32)           # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)         # [bq, D]
        lse = lse_ref[0, 0][:, :1]                    # [bq, 1]
        dlt = dlt_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where(_block_mask(qi, ki, block_q, block_k, seq_len, causal),
                      s, NEG_INF)
        p = jnp.exp(s - lse)                          # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dlt)                           # [bq, bk]
        dq_scr[:] += sm_scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _block()
    else:
        _block()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *,
                    sm_scale, causal, block_q, block_k, seq_len):
    # grid (B, H_kv, nk, group, nq): dk/dv accumulate across the GQA
    # group's q heads AND the q blocks before one narrow write — the
    # output block index is constant over both inner dims, so pallas
    # keeps it resident until the last (g, qi) visit
    ki = pl.program_id(2)
    g = pl.program_id(3)
    qi = pl.program_id(4)                             # q innermost here
    ng = pl.num_programs(3)
    nq = pl.num_programs(4)

    @pl.when(jnp.logical_and(g == 0, qi == 0))
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, dk_scr.dtype)
        dv_scr[:] = jnp.zeros(dv_scr.shape, dv_scr.dtype)

    def _block():
        q = q_ref[0, 0].astype(jnp.float32)           # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        dlt = dlt_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where(_block_mask(qi, ki, block_q, block_k, seq_len, causal),
                      s, NEG_INF)
        p = jnp.exp(s - lse)                          # [bq, bk]
        # dv += p^T @ dO
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dlt)                           # [bq, bk]
        # dk += ds^T @ q
        dk_scr[:] += sm_scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _block()
    else:
        _block()

    @pl.when(jnp.logical_and(g == ng - 1, qi == nq - 1))
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _pad_seq(x, block):
    s = x.shape[2]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x


_LANES = 128  # lse/delta carry a lane-replicated trailing dim for layout


def _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                    need_lse):
    """Returns (out [B,S,H,D], lse [B,H,Sq_padded,LANES] or None).

    `need_lse=False` (the primal/serving path) omits the lse output
    entirely — pallas outputs can't be dead-code-eliminated, so an unused
    lse would cost real HBM writes on every inference forward."""
    B, S, H, D = q.shape
    group = H // k.shape[2]   # GQA: q heads per (narrow) kv head
    qt = _pad_seq(q.transpose(0, 2, 1, 3), block_q)
    kt = _pad_seq(k.transpose(0, 2, 1, 3), block_k)
    vt = _pad_seq(v.transpose(0, 2, 1, 3), block_k)
    Sq, Sk = qt.shape[2], kt.shape[2]
    nq, nk = Sq // block_q, Sk // block_k

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=S, need_lse=need_lse)
    o_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    lse_spec = pl.BlockSpec((1, 1, block_q, _LANES),
                            lambda b, h, i, j: (b, h, i, 0))
    # narrow kv blocks are indexed by the q head's GROUP — no repeated
    # kv ever materializes in HBM (the GQA bandwidth win, kept here)
    kv_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, h, i, j: (b, h // group, j, 0))
    result = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=[o_spec] + ([lse_spec] if need_lse else []),
        out_shape=[jax.ShapeDtypeStruct(qt.shape, q.dtype)] + (
            [jax.ShapeDtypeStruct((B, H, Sq, _LANES), jnp.float32)]
            if need_lse else []),
        scratch_shapes=[
            _scratch((block_q, _LANES)),
            _scratch((block_q, _LANES)),
            _scratch((block_q, D)),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = result[0][:, :, :S].transpose(0, 2, 1, 3)
    return out, (result[1] if need_lse else None)


def _flash_bwd_impl(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k,
                    interpret, g_lse=None):
    B, S, H, D = q.shape
    group = H // k.shape[2]   # GQA: q heads per (narrow) kv head
    H_kv = k.shape[2]
    qt = _pad_seq(q.transpose(0, 2, 1, 3), block_q)
    kt = _pad_seq(k.transpose(0, 2, 1, 3), block_k)
    vt = _pad_seq(v.transpose(0, 2, 1, 3), block_k)
    dot = _pad_seq(g.transpose(0, 2, 1, 3), block_q)
    Sq, Sk = qt.shape[2], kt.shape[2]
    nq, nk = Sq // block_q, Sk // block_k

    # delta = rowsum(dO * O): [B, H, Sq] — O(B·S·H·D) elementwise, jax-side
    delta = jnp.einsum("bshd,bshd->bhs", g.astype(jnp.float32),
                       out.astype(jnp.float32))
    # an lse cotangent folds exactly into delta: ds_ij = p_ij*(dp_ij -
    # delta_i + g_lse_i), since dlse_i/ds_ij = p_ij
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    delta = jnp.pad(delta, ((0, 0), (0, 0), (0, Sq - S)))
    delta = jnp.broadcast_to(delta[..., None], (B, H, Sq, _LANES))

    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    k_spec = pl.BlockSpec((1, 1, block_k, D),
                          lambda b, h, i, j: (b, h // group, j, 0))
    r_spec = pl.BlockSpec((1, 1, block_q, _LANES),
                          lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(B, H, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[_scratch((block_q, D))],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # swap grid roles: (b, kv-head, k-block, group-member, q-block) —
    # q innermost; dk/dv come out NARROW, accumulated across the group
    # (the narrow output replaces the former repeat-then-sum cotangent)
    qk_spec = pl.BlockSpec((1, 1, block_q, D),
                           lambda b, kh, j, g, i: (b, kh * group + g, i, 0))
    kk_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, kh, j, g, i: (b, kh, j, 0))
    rk_spec = pl.BlockSpec((1, 1, block_q, _LANES),
                           lambda b, kh, j, g, i: (b, kh * group + g, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_len=S),
        grid=(B, H_kv, nk, group, nq),
        in_specs=[qk_spec, kk_spec, kk_spec, qk_spec, rk_spec, rk_spec],
        out_specs=[kk_spec, kk_spec],
        out_shape=[jax.ShapeDtypeStruct(kt.shape, k.dtype),
                   jax.ShapeDtypeStruct(vt.shape, v.dtype)],
        scratch_shapes=[_scratch((block_k, D)), _scratch((block_k, D))],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    tr = lambda x, s: x[:, :, :s].transpose(0, 2, 1, 3)
    return tr(dq, S), tr(dk, S), tr(dv, S)


def attention_reference(q, k, v, causal=True, sm_scale=None):
    """Dense reference with semantics identical to the kernel (f32 softmax,
    large-finite mask).  Used for tests and as the dense fallback.
    Accepts narrow (GQA) k/v like the kernel does — repeated here."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    D = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Sq, Sk), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                             interpret, need_lse=False)
    return out


def _flash_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                               interpret, need_lse=True)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_impl(q, k, v, out, lse, g, causal, sm_scale,
                           block_q, block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse_full = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q,
                                    block_k, interpret, need_lse=True)
    return out, lse_full[:, :, :q.shape[1], 0]


def _flash_lse_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                       interpret):
    out, lse_full = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q,
                                    block_k, interpret, need_lse=True)
    lse = lse_full[:, :, :q.shape[1], 0]
    return (out, lse), (q, k, v, out, lse_full)


def _flash_lse_vjp_bwd(causal, sm_scale, block_q, block_k, interpret, res,
                       cotangents):
    q, k, v, out, lse_full = res
    g, g_lse = cotangents
    return _flash_bwd_impl(q, k, v, out, lse_full, g, causal, sm_scale,
                           block_q, block_k, interpret, g_lse=g_lse)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def _pick_block(requested, seq_len):
    """Clamp a block size into the sequence range, then prefer the largest
    power-of-two block that DIVIDES the sequence — padding to a block
    multiple is pure masked-out waste (e.g. S=1536 at block 1024 would pad
    33% phantom rows; block 512 pads none)."""
    b = min(requested, max(seq_len, 16))
    if seq_len % b == 0:
        return b
    for cand in (1024, 512, 256, 128):
        if cand <= b and seq_len % cand == 0:
            return cand
    return b


def _resolve_call_args(q, k, sm_scale, block_q, block_k, interpret):
    """Shared prologue of the public wrappers: default scale, interpret
    auto-select (native Mosaic on TPU, interpreter elsewhere), and block
    sizes clamped into the padded sequence range.

    Default blocks are 1024x1024 — measured 28-46% faster than 512x512 on
    v5e at S in [4096, 8192] (f32 score tiles stay well inside v5e-class
    ~128MB VMEM; pre-v4 generations with small VMEM may need block sizes
    passed explicitly)."""
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"q heads {q.shape[2]} must be a multiple of kv heads "
            f"{k.shape[2]} (GQA: narrow k/v feed the kernel directly; "
            "no repeat needed)")
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        from tensorflowonspark_tpu.ops import default_interpret
        interpret = default_interpret()
    block_q = _pick_block(block_q, q.shape[1])
    block_k = _pick_block(block_k, k.shape[1])
    return float(sm_scale), int(block_q), int(block_k), bool(interpret)


def flash_attention_with_lse(q, k, v, causal=True, sm_scale=None,
                             block_q=1024, block_k=1024, interpret=None):
    """Like flash_attention but also returns the per-row logsumexp
    [B, H, S] — the merge key for combining attention computed over
    key/value shards (ring attention's per-step local compute).  Fully
    differentiable in both outputs."""
    sm_scale, block_q, block_k, interpret = _resolve_call_args(
        q, k, sm_scale, block_q, block_k, interpret)
    return _flash_lse(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def flash_attention(q, k, v, causal=True, sm_scale=None,
                    block_q=1024, block_k=1024, interpret=None):
    """Flash attention over [B, S, H, D] q and [B, S, H_kv, D] k/v.

    GQA-native: ``H_kv`` may be any divisor of ``H`` — narrow k/v blocks
    are indexed per q-head group inside the kernel, so the repeated k/v
    (and the repeat's summed cotangent) never materialize in HBM.
    Sequence lengths need not be multiples of the block sizes (padded rows
    and keys are masked out of both passes).  `interpret=None`
    auto-selects: native Mosaic on TPU, interpreter elsewhere (the CPU test
    mesh).
    """
    sm_scale, block_q, block_k, interpret = _resolve_call_args(
        q, k, sm_scale, block_q, block_k, interpret)
    return _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret)
