"""Fused unembedding + softmax cross-entropy for LM training.

The naive path (reference analog: per-example ``tf.nn.sparse_softmax_cross_
entropy_with_logits`` over a full logits tensor, e.g. reference
examples/mnist/keras models) materializes float32 logits ``[B, S, V]``
TWICE per step (forward values + backward grads).  At LM scale this is
gigabytes of HBM traffic per step — for a 32k vocab and B8xS1024, ~1 GB
forward + ~2 GB one-hot/grad machinery — and on TPU the step becomes
HBM-bound precisely at its final matmul.

`fused_unembed_xent` takes the PRE-unembedding hidden states and the
lm_head kernel and computes the loss in sequence chunks under `lax.scan`:
each chunk's logits tile lives only in registers/VMEM-scale working set,
the softmax statistics are reduced on the fly, and the backward pass
RECOMPUTES each chunk's logits instead of saving them (classic
rematerialization — trade ~1 extra chunk matmul for the full logits
round trip).  Peak extra memory is one ``[chunk, V]`` float32 tile plus
the float32 kernel-gradient accumulator.

Measured reality (BASELINE.md round 3, v5e, 0.87B/32k-vocab config): step
time is at PARITY with the materialized-logits `lm_loss` (the scan
serializes the head matmul and the backward recompute costs what the
saved logits round-trip saved), so this op is a MEMORY feature, not a
speed one: it removes the [B, S, V] float32 logits tensor from both
passes, which is what lets long-sequence / large-vocab configs fit on a
chip at all.

Sharding note: the chunk loop gathers gold logits by target id, which
assumes the vocab dimension is unsharded in this function's frame.  Under
a vocab-sharded (tp) lm_head keep using `models.transformer.lm_loss`
(gather-free one-hot einsum, partitions cleanly); this op is the
single-device / data-parallel fast path — exactly the layouts the
driver bench and the examples train in.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax


def _chunk_stats(h_c, kernel, tgt_c, mask_c):
    """Loss pieces for one chunk: (sum((logz - gold) * mask), logits fn)."""
    logits = jnp.dot(h_c, kernel, preferred_element_type=jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, tgt_c[:, None], axis=-1)[:, 0]
    return jnp.sum((logz - gold) * mask_c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_unembed_xent(hidden, kernel, targets, chunk_size=512,
                       ignore_id=-1):
    """Mean softmax cross entropy of ``hidden @ kernel`` against ``targets``
    without materializing the logits.

    hidden:  [B, S, D] (any float dtype; matmul accumulates float32)
    kernel:  [D, V] lm_head kernel (``params["lm_head"]["kernel"]``)
    targets: [B, S] int ids; positions equal to ``ignore_id`` are masked
    chunk_size: tokens per scanned tile (static)

    Matches `models.transformer.lm_loss(model(tokens), targets)` to float32
    tolerance (see tests/test_xent.py) while cutting the step's HBM
    traffic by the full forward+backward logits volume.
    """
    loss, _ = _fwd(hidden, kernel, targets, chunk_size, ignore_id)
    return loss


def _pad_chunks(flat_h, flat_t, chunk_size, ignore_id):
    T = flat_h.shape[0]
    n_chunks = -(-T // chunk_size)
    pad = n_chunks * chunk_size - T
    if pad:
        flat_h = jnp.pad(flat_h, ((0, pad), (0, 0)))
        flat_t = jnp.pad(flat_t, (0, pad), constant_values=ignore_id)
    return flat_h, flat_t, n_chunks


def _fwd(hidden, kernel, targets, chunk_size, ignore_id):
    B, S, D = hidden.shape
    flat_h = hidden.reshape(B * S, D)
    flat_t = targets.reshape(B * S)
    flat_h, flat_t, n_chunks = _pad_chunks(flat_h, flat_t, chunk_size,
                                           ignore_id)
    h_c = flat_h.reshape(n_chunks, chunk_size, D)
    t_c = flat_t.reshape(n_chunks, chunk_size)

    def body(acc, xs):
        h, t = xs
        mask = (t != ignore_id).astype(jnp.float32)
        s = _chunk_stats(h, kernel, jnp.maximum(t, 0), mask)
        return (acc[0] + s, acc[1] + jnp.sum(mask)), None

    (total, count), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (h_c, t_c))
    count = jnp.maximum(count, 1.0)
    return total / count, (hidden, kernel, targets, count)


def _bwd(chunk_size, ignore_id, res, g):
    hidden, kernel, targets, count = res
    B, S, D = hidden.shape
    V = kernel.shape[1]
    flat_h = hidden.reshape(B * S, D)
    flat_t = targets.reshape(B * S)
    flat_h, flat_t, n_chunks = _pad_chunks(flat_h, flat_t, chunk_size,
                                           ignore_id)
    h_c = flat_h.reshape(n_chunks, chunk_size, D)
    t_c = flat_t.reshape(n_chunks, chunk_size)
    scale = g / count

    def body(dk_acc, xs):
        h, t = xs
        mask = (t != ignore_id).astype(jnp.float32)
        tt = jnp.maximum(t, 0)
        logits = jnp.dot(h, kernel, preferred_element_type=jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        # d/dlogits of (logz - gold) = softmax - onehot
        dlogits = (p - jax.nn.one_hot(tt, V, dtype=jnp.float32))
        dlogits = dlogits * (mask * scale)[:, None]
        dh = jnp.dot(dlogits.astype(kernel.dtype), kernel.T,
                     preferred_element_type=jnp.float32)
        dk_acc = dk_acc + jnp.dot(h.astype(jnp.float32).T, dlogits,
                                  preferred_element_type=jnp.float32)
        return dk_acc, dh

    dk, dh_c = lax.scan(body, jnp.zeros((D, V), jnp.float32), (h_c, t_c))
    dh = dh_c.reshape(n_chunks * chunk_size, D)[:B * S]
    return (dh.reshape(B, S, D).astype(hidden.dtype),
            dk.astype(kernel.dtype), None)


fused_unembed_xent.defvjp(_fwd, _bwd)
