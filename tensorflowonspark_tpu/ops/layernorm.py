"""Pallas fused layernorm.

One VMEM pass per row-block: mean, variance, normalize, scale/shift — all
in f32 on the VPU regardless of the activation dtype, so bf16 residual
streams keep f32 normalization statistics (the standard TPU recipe the
model zoo uses via flax; this kernel fuses it for the serving/AOT path and
as the pattern for custom fusions).

Backward recomputes from saved (x, scale) via the JAX reference — O(N·D)
residuals, XLA-fused backward matmuls.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _ln_kernel(x_ref, s_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)                  # [bn, D]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    y = y * s_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def layernorm_reference(x, scale, bias, eps=1e-6):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _ln_impl(x, scale, bias, eps, block_n, interpret):
    shape = x.shape
    D = shape[-1]
    x2 = x.reshape(-1, D)
    N = x2.shape[0]
    pad = (-N) % block_n
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(x2.shape[0] // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_n, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        interpret=interpret,
    )(x2, scale, bias)
    return out[:N].reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x, scale, bias, eps, block_n, interpret):
    return _ln_impl(x, scale, bias, eps, block_n, interpret)


def _ln_vjp_fwd(x, scale, bias, eps, block_n, interpret):
    return _ln(x, scale, bias, eps, block_n, interpret), (x, scale, bias)


def _ln_vjp_bwd(eps, block_n, interpret, res, g):
    x, scale, bias = res
    _, vjp = jax.vjp(
        lambda x, s, b: layernorm_reference(x, s, b, eps), x, scale, bias)
    return vjp(g)


_ln.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def fused_layernorm(x, scale, bias, eps=1e-6, block_n=256, interpret=None):
    """Layernorm over the last dim of `x` with f32 statistics."""
    if interpret is None:
        from tensorflowonspark_tpu.ops import default_interpret
        interpret = default_interpret()
    n_rows = 1
    for d in x.shape[:-1]:
        n_rows *= d
    block_n = max(8, min(block_n, n_rows))
    return _ln(x, scale, bias, float(eps), int(block_n), bool(interpret))
