"""Single-pass fused optimizer kernels (Pallas): AdamW and Lion.

The optax update for the flagship LM is a chain of elementwise
transforms — clip -> moments -> weight decay -> lr scale -> apply — and
each link reads and writes the full f32 optimizer state in HBM.  At
0.87B params that is several complete passes over ~10 GB of state per
step, pure bandwidth the matmuls cannot hide (BENCH_r05: the optimizer
dominates the non-matmul remainder at 71.4% MFU).  These kernels apply
the ENTIRE update in one pass per parameter block:

    read  grad, param, mu[, nu]   (once)
    write param, mu[, nu]         (once)

Global-norm clipping folds in as a pre-computed scalar: one cheap
reduction pass over the gradients (``optax.global_norm``, which the
train step's metrics already compute — XLA CSEs the two), then the
scale rides into the fused apply as an SMEM scalar.  Bias corrections
and the schedule's learning rate enter the same way, so the kernel body
is a single VPU expression per block.

HBM traffic model (f32 everything, P = param count, one step):

    optax adamw chain   ~>=10 P reads/writes (clip copy, scale_by_adam
                        in/out, decayed-weights add, lr scale, apply)
    fused kernel          7 P  (4 reads + 3 writes), 5 P with bf16 mu

Exposed as an optax-compatible ``GradientTransformation`` with one
extra method:

    ``update(grads, state, params)`` -> (updates, state)   # optax protocol
    ``apply(grads, state, params)``  -> (new_params, state) # single-pass

``update`` keeps every optax composition working (tests verify parity
against ``optax.chain(clip_by_global_norm, adamw)`` step-for-step);
``apply`` additionally fuses the final ``optax.apply_updates`` add into
the kernel (the parameter write shares the pass), so no ``updates`` tree
ever materializes — the path ``parallel.train.make_train_step`` takes
automatically; the train step's jit donation recycles the old
param/moment buffers.  Both run the SAME kernel body, so the CPU test
tier (interpret=True) exercises the real kernel code.

State layout: the moments keep each parameter's exact shape and mirror
the parameter pytree (``FusedAdamWState.mu/nu``), so under explicit
shardings the state shards by the param's OWN spec — fsdp and tp axes
alike — with zero extra machinery (``parallel.train._opt_state_
shardings`` maps the mirrored tree onto the param shardings, the same
placement rule f32 optax moments get).  Blocking to the kernel's
(rows, 128) grid happens on flat views inside the jitted update, which
XLA lowers to bitcasts (plus a pad copy only for parameters whose size
is not a lane multiple — none of the flagship's are).

``mu_dtype="bfloat16"`` stores the first moment in bf16 exactly like
``optax.adamw(mu_dtype=...)`` (compute stays f32 in VMEM; the narrow
store halves that operand's traffic).  The second moment stays at the
parameter dtype, matching optax.  For MEMORY-bound settings prefer
``optim8bit.adamw8bit`` (int8 state, 4x smaller); this kernel is the
SPEED choice (fewest HBM passes, full-precision state).
"""
import functools
import math
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlibs; interpret mode needs it not
    from jax.experimental.pallas import tpu as pltpu
    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover
    pltpu = None
    _SMEM = None

LANE = 128               # TPU lane width: last dim of every block
DEFAULT_BLOCK_ROWS = 256  # (256, 128) f32 block = 128 KB per operand in VMEM
_SUBLANE = 16            # sublane multiple that tiles bf16 and f32 alike


class FusedAdamWState(NamedTuple):
    """Fused-AdamW state; mu/nu mirror the param pytree shape-for-shape
    (so state shardings mirror param shardings — see module doc)."""
    count: Any
    mu: Any
    nu: Any


class FusedLionState(NamedTuple):
    count: Any
    mu: Any


class FusedOptimizer(NamedTuple):
    """Duck-types as `optax.GradientTransformation` (init/update) with an
    extra single-pass `apply(grads, state, params) -> (params, state)`.
    NOTE: `optax.chain` strips `apply` — fold clipping/decay in via the
    constructor arguments instead of chaining."""
    init: Callable
    update: Callable
    apply: Callable


# ---------------------------------------------------------------------------
# kernels — one (block_rows, LANE) tile per grid step, everything f32 on the
# VPU; scalars (lr, clip scale, bias corrections) ride in SMEM
# ---------------------------------------------------------------------------

def _adamw_kernel(s_ref, g_ref, p_ref, mu_ref, nu_ref,
                  o_ref, mu_o_ref, nu_o_ref, *, b1, b2, eps, wd,
                  write_param):
    lr = s_ref[0, 0]
    clip = s_ref[0, 1]
    c1 = s_ref[0, 2]          # 1 - b1**t  (bias corrections, host-side pow)
    c2 = s_ref[0, 3]
    g = g_ref[:].astype(jnp.float32) * clip
    # identical expression order to optax.tree_update_moment for tight parity
    mu = (1.0 - b1) * g + b1 * mu_ref[:].astype(jnp.float32)
    nu = (1.0 - b2) * (g * g) + b2 * nu_ref[:].astype(jnp.float32)
    upd = (mu / c1) / (jnp.sqrt(nu / c2) + eps)
    if wd or write_param:
        p = p_ref[:].astype(jnp.float32)
    if wd:
        upd = upd + wd * p
    if write_param:
        o_ref[:] = (p - lr * upd).astype(o_ref.dtype)
    else:
        o_ref[:] = (-lr * upd).astype(o_ref.dtype)
    mu_o_ref[:] = mu.astype(mu_o_ref.dtype)
    nu_o_ref[:] = nu.astype(nu_o_ref.dtype)


def _lion_kernel(s_ref, g_ref, p_ref, mu_ref, o_ref, mu_o_ref,
                 *, b1, b2, wd, write_param):
    lr = s_ref[0, 0]
    clip = s_ref[0, 1]
    g = g_ref[:].astype(jnp.float32) * clip
    mu = mu_ref[:].astype(jnp.float32)
    upd = jnp.sign((1.0 - b1) * g + b1 * mu)     # sign of the interpolation
    new_mu = (1.0 - b2) * g + b2 * mu            # the stored momentum
    if wd or write_param:
        p = p_ref[:].astype(jnp.float32)
    if wd:
        upd = upd + wd * p
    if write_param:
        o_ref[:] = (p - lr * upd).astype(o_ref.dtype)
    else:
        o_ref[:] = (-lr * upd).astype(o_ref.dtype)
    mu_o_ref[:] = new_mu.astype(mu_o_ref.dtype)


# ---------------------------------------------------------------------------
# per-leaf driver: flatten to (rows, LANE), pad the tail block, run the grid
# ---------------------------------------------------------------------------

def _block_rows_for(n, block_rows):
    """Rows per grid step: the default, shrunk for small params so a bias
    vector does not pad out to a full block (sublane-multiple so one tile
    size serves f32 and bf16 operands)."""
    rows = -(-n // LANE)
    return min(block_rows, -(-rows // _SUBLANE) * _SUBLANE)


def _to_blocks(x, bm):
    flat = x.reshape(-1)
    per = bm * LANE
    padded = -(-flat.shape[0] // per) * per
    if padded != flat.shape[0]:
        flat = jnp.pad(flat, (0, padded - flat.shape[0]))
    return flat.reshape(-1, LANE)


def _from_blocks(y, shape):
    n = math.prod(shape) if shape else 1
    return y.reshape(-1)[:n].reshape(shape)


def _run_leaf(kernel, scalars, arrays, out_dtypes, block_rows, interpret):
    """Run `kernel` over same-shaped leaf `arrays` blocked to (bm, LANE).

    `arrays[0]` supplies the logical shape; outputs are the first
    `len(out_dtypes)` kernel refs after the inputs, unpadded back to it.
    Padding lanes hold zeros; both kernels map zero grad/state to zero
    output (eps keeps the adam quotient finite), so the pad never NaNs.

    Two deliberate sharding choices, both found the hard way on the
    8-device mesh: (1) NO pallas-level input_output_aliases — under GSPMD
    the compiler may pick different shardings for the flattened operand
    and its output, and the runtime alias check then fails on mismatched
    per-shard sizes; aliasing only saves a buffer allocation, not HBM
    traffic (the read+write still happen exactly once here), and the
    train step's jit donation already recycles the old state buffers.
    (2) every output is pinned to its input's sharding via shard_alike —
    the flatten/unflatten reshapes break GSPMD's propagation, and a
    freshly-chosen output sharding makes the train step's donated state
    aliases fail the same way.
    """
    from jax.experimental.shard_alike import shard_alike

    shape = arrays[0].shape
    n = math.prod(shape) if shape else 1
    bm = _block_rows_for(n, block_rows)
    blocks = [_to_blocks(a, bm) for a in arrays]
    rows = blocks[0].shape[0]
    bspec = pl.BlockSpec((bm, LANE), lambda i: (i, 0))
    if _SMEM is not None:
        sspec = pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=_SMEM)
    else:  # pragma: no cover - CPU-only jaxlib
        # interpret-mode only (no TPU ext -> no SMEM): a (1, 4) scalar
        # block is never vector-tiled here
        # graftcheck: disable-next-line=pallas-tile
        sspec = pl.BlockSpec((1, 4), lambda i: (0, 0))
    outs = pl.pallas_call(
        kernel,
        grid=(rows // bm,),
        in_specs=[sspec] + [bspec] * len(blocks),
        out_specs=[bspec] * len(out_dtypes),
        out_shape=[jax.ShapeDtypeStruct((rows, LANE), d)
                   for d in out_dtypes],
        interpret=interpret,
    )(scalars, *blocks)
    outs = [_from_blocks(o, shape) for o in outs]
    # outputs correspond positionally to the TRAILING inputs (adamw:
    # out/new_mu/new_nu <- p/mu/nu; lion: out/new_mu <- p/mu)
    srcs = arrays[len(arrays) - len(outs):]
    return tuple(shard_alike(s, o)[1] for s, o in zip(srcs, outs))


# ---------------------------------------------------------------------------
# transforms
# ---------------------------------------------------------------------------

def _resolve(value, params):
    return value(params) if callable(value) else value


def _decay_tree(params, weight_decay, mask):
    """Static per-leaf weight decay (the mask routes decay away from
    biases/norms; leaves must be static bools — they pick the compiled
    kernel variant)."""
    if not weight_decay:
        return jax.tree_util.tree_map(lambda _: 0.0, params)
    if mask is None:
        return jax.tree_util.tree_map(lambda _: float(weight_decay), params)
    m = _resolve(mask, params)
    return jax.tree_util.tree_map(
        lambda flag: float(weight_decay) if flag else 0.0, m)


def _scalars(learning_rate, count, clip_norm, b1, b2, updates):
    """Pack (lr, clip_scale, 1-b1^t, 1-b2^t) as the kernels' SMEM operand.
    One global-norm reduction when clipping — the only non-fused pass."""
    import optax

    lr = _resolve(learning_rate, count)
    t = optax.safe_int32_increment(count).astype(jnp.float32)
    if clip_norm:
        g_norm = optax.global_norm(updates)
        # optax.clip_by_global_norm: identity below the threshold, exact
        # max_norm/g_norm scale above it
        clip = jnp.where(g_norm < clip_norm, 1.0,
                         clip_norm / g_norm)
    else:
        clip = 1.0
    return jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(clip, jnp.float32),
                      1.0 - b1 ** t,
                      1.0 - b2 ** t]).reshape(1, 4)


def _interpret_flag(interpret):
    if interpret is None:
        from tensorflowonspark_tpu.ops import default_interpret
        return default_interpret()
    return bool(interpret)


def adamw_fused(learning_rate, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, mask=None, clip_norm=None, mu_dtype=None,
                block_rows=DEFAULT_BLOCK_ROWS, interpret=None):
    """Fused AdamW: matches ``optax.chain(clip_by_global_norm(clip_norm),
    adamw(...))`` step-for-step (tests assert rtol ~1e-6 in f32) while
    touching HBM once per operand.  ``learning_rate`` may be a schedule
    (called with the update count, optax convention).  See module doc for
    the ``update`` vs ``apply`` split."""
    mu_dtype = jnp.dtype(mu_dtype) if mu_dtype is not None else None

    def init_fn(params):
        # zeros_like, not zeros: it inherits each param's placement, so
        # moments created from already-sharded params land sharded too
        return FusedAdamWState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype),
                params),
            nu=jax.tree_util.tree_map(jnp.zeros_like, params))

    def _run(updates, state, params, write_param):
        if params is None:
            if weight_decay:
                raise ValueError(
                    "adamw_fused with weight_decay requires params "
                    "(optax convention: update(grads, state, params))")
            if write_param:
                raise ValueError("apply() requires params")
            params = updates     # placeholder operand; kernels skip p reads
        interp = _interpret_flag(interpret)
        scal = _scalars(learning_rate, state.count, clip_norm, b1, b2,
                        updates)
        wds = _decay_tree(updates, weight_decay, mask)

        def leaf(g, p, mu, nu, wd):
            kern = functools.partial(
                _adamw_kernel, b1=float(b1), b2=float(b2), eps=float(eps),
                wd=float(wd), write_param=write_param)
            out_dtype = p.dtype if write_param else g.dtype
            out, new_mu, new_nu = _run_leaf(
                kern, scal, [g, p, mu, nu],
                [out_dtype, mu.dtype, nu.dtype], block_rows, interp)
            return _LeafOut(out, new_mu, new_nu)

        flat = jax.tree_util.tree_map(leaf, updates, params, state.mu,
                                      state.nu, wds)
        is_out = lambda x: isinstance(x, _LeafOut)  # noqa: E731
        import optax
        new_state = FusedAdamWState(
            count=optax.safe_int32_increment(state.count),
            mu=jax.tree_util.tree_map(lambda t: t.mu, flat, is_leaf=is_out),
            nu=jax.tree_util.tree_map(lambda t: t.nu, flat, is_leaf=is_out))
        out = jax.tree_util.tree_map(lambda t: t.out, flat, is_leaf=is_out)
        return out, new_state

    def update_fn(updates, state, params=None):
        return _run(updates, state, params, write_param=False)

    def apply_fn(updates, state, params):
        return _run(updates, state, params, write_param=True)

    return FusedOptimizer(init_fn, update_fn, apply_fn)


def lion_fused(learning_rate, b1=0.9, b2=0.99, weight_decay=0.0, mask=None,
               clip_norm=None, mu_dtype=None,
               block_rows=DEFAULT_BLOCK_ROWS, interpret=None):
    """Fused Lion (sign-momentum): matches ``optax.chain(clip_by_global_
    norm, lion(...))``; half the moment state of AdamW and the same
    single-pass traffic model."""
    mu_dtype = jnp.dtype(mu_dtype) if mu_dtype is not None else None

    def init_fn(params):
        # zeros_like inherits each param's placement (see adamw_fused)
        return FusedLionState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype),
                params))

    def _run(updates, state, params, write_param):
        if params is None:
            if weight_decay:
                raise ValueError(
                    "lion_fused with weight_decay requires params")
            if write_param:
                raise ValueError("apply() requires params")
            params = updates
        interp = _interpret_flag(interpret)
        scal = _scalars(learning_rate, state.count, clip_norm, b1, b2,
                        updates)
        wds = _decay_tree(updates, weight_decay, mask)

        def leaf(g, p, mu, wd):
            kern = functools.partial(
                _lion_kernel, b1=float(b1), b2=float(b2), wd=float(wd),
                write_param=write_param)
            out_dtype = p.dtype if write_param else g.dtype
            out, new_mu = _run_leaf(
                kern, scal, [g, p, mu], [out_dtype, mu.dtype],
                block_rows, interp)
            return _LeafOut(out, new_mu, None)

        flat = jax.tree_util.tree_map(leaf, updates, params, state.mu, wds)
        is_out = lambda x: isinstance(x, _LeafOut)  # noqa: E731
        import optax
        new_state = FusedLionState(
            count=optax.safe_int32_increment(state.count),
            mu=jax.tree_util.tree_map(lambda t: t.mu, flat, is_leaf=is_out))
        out = jax.tree_util.tree_map(lambda t: t.out, flat, is_leaf=is_out)
        return out, new_state

    def update_fn(updates, state, params=None):
        return _run(updates, state, params, write_param=False)

    def apply_fn(updates, state, params):
        return _run(updates, state, params, write_param=True)

    return FusedOptimizer(init_fn, update_fn, apply_fn)


class _LeafOut(NamedTuple):
    """Per-leaf kernel results (a dedicated type so tree_map's is_leaf
    cannot collide with tuple containers inside the user's param pytree —
    same device as optim8bit._UpdOut)."""
    out: Any
    mu: Any
    nu: Any
