"""Pallas paged flash-decode attention: read kv pages in place.

The paged slot cache (models/transformer._paged_attention_body) keeps kv
in a shared pool ``pages_key/pages_value [kv_pages, page, n_kv, Dh]``
with a per-row ``page_table [B, max_pages]`` naming each row's pages.
The reference read path gathers every row's FULL logical ``[max_seq,
n_kv, Dh]`` view out of the pool (``jnp.take`` over the whole table),
materializes the GQA head expansion, and softmaxes over ``max_seq``
masked positions — O(max_seq) HBM traffic per decoded token regardless
of how many tokens each row actually holds.

This kernel is the vLLM-PagedAttention / Flash-Decoding fix:

- the page table and per-row lengths are SCALAR-PREFETCHED
  (``pltpu.PrefetchScalarGridSpec``), so each kv BlockSpec index_map
  looks the physical page up and DMAs it straight out of the pool — no
  logical-view gather ever materializes;
- q heads are grouped onto their kv head inside the kernel (the block
  holds one kv head's whole GQA group), so the repeated kv of
  ``_kv_repeat`` never exists in HBM;
- pages past a row's true length are never read: the index_map clamps
  the page index at the row's last occupied page (consecutive grid
  steps then name the SAME block, whose re-fetch Pallas elides) and
  ``pl.when`` skips their compute entirely;
- online softmax (running max / denominator / accumulator in VMEM
  scratch, f32) over the visited pages only;
- split-K over the page axis: each split emits an unnormalized partial
  (acc, m, l) and a jax-side logsumexp combine merges them — the
  flash-decoding shape that keeps long-context single-token decode from
  serializing over one long page walk;
- int8 kv dequantizes INSIDE the page read (payload block + per-token
  scale block, multiplied after the f32 cast), so the wide cache never
  exists anywhere;
- ``interpret=`` threads through (ops.default_interpret()), so the CPU
  tier executes this exact kernel body in the Pallas interpreter.

Layout notes: block shapes are built from runtime dims (``page``,
``Dh``, ``ROWS``) — on TPU, best layouts want head_dim a multiple of
128 and page_size a multiple of the dtype sublane tile (8 f32 / 16 bf16
/ 32 int8); any sizes are CORRECT, Mosaic pads the rest.  The int8
scale pools are transposed to ``[kv_pages, n_kv, page]`` before the
call so their minor dim is the page axis — a per-step copy of the
scale arrays only (4/Dh of the int8 payload bytes, ~3% at Dh=128),
never of the payload pool.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-enabled jaxlibs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30  # large-finite: exp(NEG_INF - m) == 0 without inf-inf NaNs
_LANES = 128     # m/l carry a lane-replicated trailing dim for layout


def paged_attention_available():
    """True when the TPU pallas extension (scalar prefetch) imported —
    callers fall back to the einsum reference read otherwise."""
    return pltpu is not None


def _scratch(shape, dtype=jnp.float32):
    if _VMEM is not None:
        return pltpu.VMEM(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype)  # pragma: no cover


def _pick_splits(requested, max_pages):
    """Largest split count <= requested that DIVIDES the page axis (a
    ragged tail split would need its own masked page range for zero
    win; every divisor keeps the per-split walk uniform)."""
    for cand in range(min(int(requested), max_pages), 1, -1):
        if max_pages % cand == 0:
            return cand
    return 1


def _decode_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                   sm_scale, page, s_chunk, group, n_per, quant):
    if quant:
        ks_ref, vs_ref = rest[:2]
        rest = rest[2:]
    acc_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    sp = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, m_scr.dtype)
        l_scr[:] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[:] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    pidx = sp * n_per + j          # logical page this grid step covers
    n_tok = len_ref[b]             # row's written length (incl. chunk)

    # only occupied pages are visited: everything at or past the row's
    # length bound skips compute (its DMA was clamped to the last
    # occupied page by the index_map, which pallas elides as a re-fetch)
    @pl.when(pidx * page < n_tok)
    def _visit():
        q = q_ref[0, 0].astype(jnp.float32)          # [ROWS, Dh]
        k = k_ref[0, :, 0, :].astype(jnp.float32)    # [page, Dh]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quant:
            # int8 dequant fused into the page read: payload * per-token
            # scale, after the f32 cast (the wide kv never materializes)
            k = k * ks_ref[0, 0][:, None]
            v = v * vs_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        # row r of the grouped q block is (query s_chunk-pos r//group,
        # group member r%group); key j is visible iff j <= idx + s with
        # idx = n_tok - s_chunk (the slot-cache visibility rule)
        k_pos = pidx * page + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
        s = jnp.where(k_pos <= (n_tok - s_chunk) + q_pos, s, NEG_INF)

        m_prev = m_scr[:, :1]                        # [ROWS, 1]
        l_prev = l_scr[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                       # [ROWS, page]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_per - 1)
    def _finish():
        acc_ref[0, 0, 0] = acc_scr[:]
        m_ref[0, 0, 0] = m_scr[:]
        l_ref[0, 0, 0] = l_scr[:]


def paged_attention(q, pages_key, pages_value, page_table, lengths, *,
                    key_scales=None, value_scales=None, sm_scale=None,
                    k_splits=8, interpret=None):
    """Flash-decode attention over an in-place paged kv pool.

    Args:
      q: ``[B, S, H, Dh]`` query chunk (S=1 decode steps, S>1 prefill
        chunks).
      pages_key / pages_value: the pool, ``[kv_pages, page, n_kv, Dh]``
        — activation dtype, or int8 with ``key_scales``/``value_scales``
        ``[kv_pages, page, n_kv]`` f32 (per-(token, head) symmetric
        scales, transformer._kv_quantize's storage form).
      page_table: ``[B, max_pages]`` int32 physical page per logical
        block.  Entries past a row's length are never read (the walk is
        clamped at the row's last occupied page).
      lengths: ``[B]`` int32 — tokens WRITTEN per row, including the
        current chunk (the post-write cache_index).  Query position s
        sees key j iff ``j <= lengths - S + s``; rows must satisfy
        ``lengths >= S`` (queries with no visible key — possible only
        below that — get unspecified values; ``lengths == 0`` rows
        return exact zeros).
      k_splits: target split-K parallelism over the page axis (clamped
        to a divisor of max_pages).

    Returns ``[B, S, H, Dh]`` in q's dtype.
    """
    if pltpu is None:  # pragma: no cover
        raise RuntimeError(
            "paged_attention needs jax.experimental.pallas.tpu (scalar "
            "prefetch); use the einsum read path "
            "(TransformerConfig.paged_attn_impl='einsum') instead")
    B, S, H, Dh = q.shape
    NP, page, n_kv, Dh_kv = pages_key.shape
    if pages_value.shape != pages_key.shape or Dh_kv != Dh:
        raise ValueError(
            f"pool shapes {pages_key.shape} / {pages_value.shape} must "
            f"match and end in head_dim {Dh}")
    if H % n_kv:
        raise ValueError(
            f"q heads {H} must be a multiple of kv heads {n_kv} (GQA "
            "groups map onto their kv head inside the kernel)")
    quant = pages_key.dtype == jnp.int8
    if quant and (key_scales is None or value_scales is None):
        raise ValueError("int8 pools need key_scales and value_scales "
                         "[kv_pages, page, n_kv]")
    if not quant and (key_scales is not None or value_scales is not None):
        raise ValueError("scales are only meaningful for int8 pools")
    max_pages = page_table.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (Dh ** 0.5)
    if interpret is None:
        from tensorflowonspark_tpu.ops import default_interpret
        interpret = default_interpret()

    group = H // n_kv
    rows = S * group
    # grouped-q rows pad to the sublane tile of q's dtype
    mult = 8 if q.dtype == jnp.float32 else 16
    ROWS = max(mult, -(-rows // mult) * mult)
    q_r = q.reshape(B, S, n_kv, group, Dh).transpose(0, 2, 1, 3, 4)
    q_r = q_r.reshape(B, n_kv, rows, Dh)
    if ROWS != rows:
        q_r = jnp.pad(q_r, ((0, 0), (0, 0), (0, ROWS - rows), (0, 0)))

    n_splits = _pick_splits(k_splits, max_pages)
    n_per = max_pages // n_splits
    table = page_table.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    def _page_idx(b, h, sp, j, table_ref, len_ref):
        # clamp at the row's last occupied page so out-of-bound grid
        # steps re-name the previous block (pallas elides the re-fetch)
        pidx = sp * n_per + j
        last = jnp.maximum(len_ref[b] - 1, 0) // page
        return table_ref[b, jnp.minimum(pidx, last)]

    q_spec = pl.BlockSpec((1, 1, ROWS, Dh),
                          lambda b, h, sp, j, tr, lr: (b, h, 0, 0))
    kv_spec = pl.BlockSpec(
        (1, page, 1, Dh),
        lambda b, h, sp, j, tr, lr: (_page_idx(b, h, sp, j, tr, lr),
                                     0, h, 0))
    out_spec = pl.BlockSpec((1, 1, 1, ROWS, Dh),
                            lambda b, h, sp, j, tr, lr: (b, h, sp, 0, 0))
    red_spec = pl.BlockSpec((1, 1, 1, ROWS, _LANES),
                            lambda b, h, sp, j, tr, lr: (b, h, sp, 0, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [q_r, pages_key, pages_value]
    if quant:
        # minor-dim = page axis so the scale blocks are lane-tiled; this
        # copies the (small) scale arrays only, never the payload pool
        sc_spec = pl.BlockSpec(
            (1, 1, page),
            lambda b, h, sp, j, tr, lr: (_page_idx(b, h, sp, j, tr, lr),
                                         h, 0))
        in_specs += [sc_spec, sc_spec]
        inputs += [key_scales.transpose(0, 2, 1),
                   value_scales.transpose(0, 2, 1)]

    kernel = functools.partial(
        _decode_kernel, sm_scale=float(sm_scale), page=page, s_chunk=S,
        group=group, n_per=n_per, quant=quant)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_kv, n_splits, n_per),
        in_specs=in_specs,
        out_specs=[out_spec, red_spec, red_spec],
        scratch_shapes=[
            _scratch((ROWS, _LANES)),
            _scratch((ROWS, _LANES)),
            _scratch((ROWS, Dh)),
        ])
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, n_kv, n_splits, ROWS, Dh),
                                 jnp.float32),
            jax.ShapeDtypeStruct((B, n_kv, n_splits, ROWS, _LANES),
                                 jnp.float32),
            jax.ShapeDtypeStruct((B, n_kv, n_splits, ROWS, _LANES),
                                 jnp.float32),
        ],
        interpret=interpret,
    )(table, lengths, *inputs)

    # LSE combine across splits: out = sum_s e^{m_s - M} acc_s /
    # sum_s e^{m_s - M} l_s.  Splits past a row's pages carry (m=-inf,
    # l=0, acc=0) and drop out; rows with NO visible key anywhere
    # (lengths == 0) hit the denominator guard and return exact zeros.
    m0, l0 = m[..., 0], l[..., 0]            # [B, n_kv, splits, ROWS]
    mx = jnp.max(m0, axis=2)
    w = jnp.exp(m0 - mx[:, :, None])
    denom = jnp.maximum(jnp.sum(w * l0, axis=2), 1e-30)
    out = jnp.sum(w[..., None] * acc, axis=2) / denom[..., None]
    out = out[:, :, :rows].reshape(B, n_kv, S, group, Dh)
    return out.transpose(0, 2, 1, 3, 4).reshape(B, S, H, Dh).astype(q.dtype)


def paged_attention_reference(q, pages_key, pages_value, page_table,
                              lengths, *, key_scales=None,
                              value_scales=None, sm_scale=None):
    """Dense gather reference with the kernel's exact semantics (f32
    softmax, large-finite mask, lengths-relative visibility) — the
    oracle for the parity tests, shaped like the einsum read body in
    models/transformer._paged_attention_body.  Rows with ``lengths ==
    0`` return zeros, matching the kernel's empty-row definition."""
    B, S, H, Dh = q.shape
    NP, page, n_kv, _ = pages_key.shape
    L = page_table.shape[1] * page
    if sm_scale is None:
        sm_scale = 1.0 / (Dh ** 0.5)
    kb = jnp.take(pages_key, page_table, axis=0)   # [B, mp, page, n_kv, Dh]
    vb = jnp.take(pages_value, page_table, axis=0)
    if pages_key.dtype == jnp.int8:
        ks = jnp.take(key_scales, page_table, axis=0)
        vs = jnp.take(value_scales, page_table, axis=0)
        kb = kb.astype(jnp.float32) * ks[..., None]
        vb = vb.astype(jnp.float32) * vs[..., None]
    kf = kb.reshape(B, L, n_kv, Dh).astype(jnp.float32)
    vf = vb.reshape(B, L, n_kv, Dh).astype(jnp.float32)
    if n_kv != H:
        kf = jnp.repeat(kf, H // n_kv, axis=2)
        vf = jnp.repeat(vf, H // n_kv, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        kf) * sm_scale
    idx = lengths - S
    visible = (jnp.arange(L)[None, None, :]
               <= (idx[:, None, None] + jnp.arange(S)[None, :, None]))
    logits = jnp.where(visible[:, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vf)
    out = jnp.where(lengths[:, None, None, None] > 0, out, 0.0)
    return out.astype(q.dtype)
