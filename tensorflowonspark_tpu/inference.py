"""Batch-inference CLI over TFRecord shards and an exported model.

Maps the reference's JVM inference driver
(reference: src/main/scala/com/yahoo/tensorflowonspark/Inference.scala:30-43
args, :52-79 load TFRecords -> TFModel.transform -> write JSON): reads
TFRecord files, runs the exported model — preferring the AOT/native PJRT
engine when the artifact carries one — and writes JSON-lines output, one
file per input shard.

    python -m tensorflowonspark_tpu.inference \
        --export_dir /models/mnist --input data/mnist/tfrecords \
        --schema_hint 'struct<image:array<float>,label:long>' \
        --input_mapping '{"image": "image"}' \
        --output_mapping '{"logits": "prediction"}' \
        --output /tmp/predictions [--engine auto|native|jax]
"""
import argparse
import json
import logging

logger = logging.getLogger(__name__)


def build_argparser():
    p = argparse.ArgumentParser(
        prog="tensorflowonspark_tpu.inference",
        description="batch inference over TFRecords (Inference.scala analog)")
    p.add_argument("--export_dir", required=True,
                   help="saved-model dir (export.export_saved_model)")
    p.add_argument("--input", required=True,
                   help="TFRecord file, dir, or glob")
    p.add_argument("--output", required=True, help="output dir (JSON lines)")
    p.add_argument("--schema_hint", default=None,
                   help="struct<name:type,...> to type the decoded features")
    p.add_argument("--input_mapping", default=None,
                   help='JSON {feature_name: model_input_name}')
    p.add_argument("--output_mapping", default=None,
                   help='JSON {model_output_name: result_column}')
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--signature_def_key", default=None)
    p.add_argument("--engine", choices=["auto", "native", "jax", "builder"],
                   default="auto",
                   help="auto: AOT artifact if present (native PJRT runner "
                        "when available), else rebuild from the model spec")
    p.add_argument("--verbose", action="store_true")
    return p


def _input_files(pattern):
    from . import fsio, tfrecord
    if fsio.isdir(pattern):
        files = fsio.glob(fsio.join(pattern, "*.tfrecord")) or \
            fsio.glob(fsio.join(pattern, "part-*"))
    else:
        files = fsio.glob(pattern)
    # random-access sidecars (saveAsTFRecords(index=True)) are not shards
    files = [f for f in files if not f.endswith(tfrecord.INDEX_SUFFIX)]
    if not files:
        raise FileNotFoundError(f"no input files match {pattern!r}")
    return files


def _decode_shard(path, fields):
    """TFRecord shard -> {feature: list_of_values} honoring the schema hint
    (reference DFUtil.loadTFRecords + schemaHint, DFUtil.scala:35-110)."""
    import numpy as np

    from . import tfrecord

    columns = {}
    count = 0
    for ex in tfrecord.read_examples(path):
        missing = [n for n in columns if n not in ex]
        if missing:
            # tf.train.Example allows sparse features, but a tabular batch
            # cannot: silently skipping would misalign rows across columns
            raise ValueError(
                f"{path}: example {count} is missing feature(s) {missing}; "
                "all examples in a shard must carry the same features")
        for name, (kind, values) in ex.items():
            if count and name not in columns:
                raise ValueError(
                    f"{path}: example {count} introduces new feature "
                    f"{name!r} absent from earlier examples")
            f = fields.get(name) if fields else None
            if f is None:
                value = values if kind != "bytes" or len(values) != 1 else values[0]
            elif f.dtype == "string":
                value = (values[0].decode("utf-8", "replace")
                         if values and isinstance(values[0], bytes) else values)
            elif f.dtype == "binary":
                value = values[0] if len(values) == 1 else values
            elif f.is_array:
                value = np.asarray(values, f.dtype)
            else:
                value = np.asarray(values, f.dtype).reshape(-1)[0] if values else None
            columns.setdefault(name, []).append(value)
        count += 1
    return columns, count


def _load_predictor(args):
    """Return (predict_rows(columns) -> {out_col: list}, description)."""
    from . import aot, export

    signature = None
    spec_inputs = None
    in_map = json.loads(args.input_mapping) if args.input_mapping else None
    out_map = json.loads(args.output_mapping) if args.output_mapping else None

    use_aot = args.engine in ("auto", "native", "jax") and aot.has_aot(args.export_dir)
    if args.engine in ("native", "jax") and not use_aot:
        raise ValueError(
            f"--engine {args.engine} requires an AOT artifact "
            f"({args.export_dir}/aot); re-export with aot_batch_sizes")

    if use_aot:
        engine = args.engine if args.engine != "auto" else "auto"
        predict, spec, bs = aot.load_aot(args.export_dir,
                                         batch_size=args.batch_size,
                                         engine=engine)
        spec_inputs = [(i["name"], i) for i in spec["inputs"]]
        out_names = spec["outputs"]
        desc = f"aot(batch={bs})"

        def predict_rows(columns, n):
            import numpy as np

            arrays = []
            inv = {v: k for k, v in (in_map or {}).items()}
            for name, meta in spec_inputs:
                feat = inv.get(name, name)
                col = columns.get(feat)
                if col is None:
                    raise KeyError(
                        f"model input {name!r} not fed: no feature {feat!r} "
                        f"(have {sorted(columns)})")
                arr = np.asarray(col, dtype=meta["dtype"])
                arr = arr.reshape((n,) + tuple(int(d) for d in meta["shape"]))
                arrays.append(arr)
            outs = aot.predict_batched(predict, arrays, bs)
            return _name_outputs(outs, out_names, out_map)

        # feature names as fed (post input_mapping inversion), so bare-row
        # requests key their column the way predict_rows looks it up
        _inv = {v: k for k, v in (in_map or {}).items()}
        predict_rows.input_names = [_inv.get(name, name)
                                    for name, _ in spec_inputs]
    else:
        import jax

        apply_fn, params, signature = export.load_saved_model(
            args.export_dir, args.signature_def_key)
        jit_apply = jax.jit(apply_fn)
        out_names = signature.get("outputs", ["output"])
        bs = max(1, int(getattr(args, "batch_size", 64) or 64))
        desc = f"builder(batch={bs})"

        def _apply_chunk(chunk):
            outs = jit_apply(params, *chunk)
            return outs if isinstance(outs, (tuple, list)) else (outs,)

        def predict_rows(columns, n):
            cols = {}
            inv = {v: k for k, v in (in_map or {}).items()}
            for name in signature["inputs"]:
                feat = inv.get(name, name)
                if feat not in columns:
                    raise KeyError(
                        f"model input {name!r} not fed: no feature {feat!r} "
                        f"(have {sorted(columns)})")
                cols[name] = columns[feat]
            arrays = export.coerce_inputs(signature, cols)
            # split/repeat-pad to the fixed compile batch so novel request
            # sizes never trigger an XLA recompile inside the request path
            outs = aot.predict_batched(_apply_chunk, arrays, bs)
            return _name_outputs(outs, out_names, out_map)

        _inv = {v: k for k, v in (in_map or {}).items()}
        predict_rows.input_names = [_inv.get(name, name)
                                    for name in signature["inputs"]]

    return predict_rows, desc


def _name_outputs(outs, out_names, out_map):
    import numpy as np

    named = {}
    for name, arr in zip(out_names, outs):
        if out_map and name not in out_map:
            continue
        named[(out_map or {}).get(name, name)] = np.asarray(arr)
    return named


def main(argv=None):
    args = build_argparser().parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)

    from . import schema as schema_mod

    fields = None
    if args.schema_hint:
        fields = {f.name: f for f in schema_mod.parse_struct(args.schema_hint)}

    files = _input_files(args.input)
    predict_rows, desc = _load_predictor(args)
    logger.info("inference over %d shards with engine %s", len(files), desc)

    from . import fsio

    fsio.makedirs(args.output)
    total = 0
    for i, path in enumerate(files):
        columns, n = _decode_shard(path, fields)
        out_path = fsio.join(args.output, f"part-{i:05d}.json")
        if n == 0:
            fsio.fopen(out_path, "w").close()
            continue
        named = predict_rows(columns, n)
        with fsio.fopen(out_path, "w") as out:
            for r in range(n):
                row = {k: v[r].tolist() for k, v in named.items()}
                out.write(json.dumps(row) + "\n")
        total += n
    print(f"wrote {total} predictions to {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
