"""Shared-memory data plane for the executor feed path.

The reference moves every record through `multiprocessing.managers`
queue proxies — each put/get serializes the payload through a socket to
the manager server process (reference: TFManager.py:51-65), which tops
out around 10 MB/s. This module keeps that queue for what it is good at
— ordering, `task_done`/`join` accounting, and the `None`/`EndPartition`
marker protocol — and moves the *bytes* through a named
`multiprocessing.shared_memory` slot ring instead (SURVEY.md §7
"process-boundary feed throughput"):

    feeder process                       node (consumer) process
    ------------------                   -----------------------
    encode chunk -> ring.write() ---\\    q.get() -> ShmRef
    q.put(ShmRef(seq, ...))  --------+-> ring.read(ref) -> chunk
                                     |   q.task_done()
         [payload: one memcpy into   |
          /dev/shm, one memcpy out]  |
         [queue: ~100-byte ref]   ---/

Design points:

- **Slot ring, byte-granular frames.** The segment is `nslots` fixed
  slots plus a header page. A payload occupies `ceil(nbytes/slot_bytes)`
  consecutive slots (by sequence number, wrapping). Per-slot state is a
  single byte (0=free, 1=full): single-byte stores are atomic, so no
  cross-process locks are needed for the one-producer-at-a-time /
  one-consumer discipline the executor feed already guarantees (Spark
  runs one task per executor core; LocalBackend serializes tasks per
  executor the same way).
- **Sequence numbers live in the segment**, so successive feeder *tasks*
  (separate short-lived processes) continue where the previous one left
  off. Concurrent producers on one node are NOT supported — same
  constraint the reference's EndPartition accounting already imposes.
- **Refs ride the queue** (`ShmRef`), so FIFO order, backpressure-on-
  join, error propagation, and `terminate()` draining all keep their
  reference semantics; a drained ref is `skip()`ed to free its slots.
- **Payloads are columnar.** `encode_chunk` writes a tiny pickled meta
  header plus the raw column buffers of a `marker.PackedChunk`;
  non-packable chunks fall back to one pickle blob — still a single
  memcpy through the ring rather than a socket write.

The ring is created by the node bootstrap before registration and
advertised through the manager kv store under ``shm_ring``; producers
and consumers attach by name. `TFOS_TPU_SHM_RING=0` disables the data
plane (the queue then carries whole chunks, as in round 1);
`TFOS_TPU_RING_MB` sizes it (default 64).
"""
import json
import logging
import os
import pickle
import struct
import threading
import time
import uuid

from . import marker

logger = logging.getLogger(__name__)

_MAGIC = 0x54464F53524E4731  # "TFOSRNG1"
_HEADER_BYTES = 4096
_STATE_OFF = 64          # per-slot state bytes start here
_FREE, _FULL = 0, 1

DEFAULT_RING_MB = 64
# finer slots bound fragmentation: a payload wastes at most one slot
DEFAULT_NSLOTS = 64


class RingTimeout(TimeoutError):
    """The consumer did not free ring space within the wait budget."""


class ShmRef:
    """Queue-borne reference to a payload in the ring.

    ``seq`` is the first frame's sequence number, ``nframes`` how many
    consecutive frames it spans, ``nbytes`` the payload length, and
    ``count`` the record count (so accounting needs no decode).
    """

    __slots__ = ("seq", "nframes", "nbytes", "count")

    def __init__(self, seq, nframes, nbytes, count):
        self.seq = seq
        self.nframes = nframes
        self.nbytes = nbytes
        self.count = count

    def __len__(self):
        return self.count

    def __repr__(self):
        return (f"ShmRef(seq={self.seq}, frames={self.nframes}, "
                f"bytes={self.nbytes}, n={self.count})")

    def __reduce__(self):
        return (ShmRef, (self.seq, self.nframes, self.nbytes, self.count))


RING_FILE = ".tfos_shm_ring"


def advertise_file(info, workdir=None):
    """Drop the ring coordinates next to the executor-id file, so feeders
    and the node process (whose cwd is the executor dir, like the
    reference's executor-id trick, reference: util.py:77-94) can discover
    the ring without a manager kv round trip (~0.2 s of AutoProxy setup
    per feeder task)."""
    path = os.path.join(workdir or os.getcwd(), RING_FILE)
    with open(path, "w") as f:
        json.dump(info, f)


def remove_advertisement(workdir=None):
    try:
        os.remove(os.path.join(workdir or os.getcwd(), RING_FILE))
    except OSError:
        pass


def discover(mgr=None, workdir=None):
    """Ring info from the cwd file (fast path) or the manager kv store
    (set alongside the file; survives callers with a different cwd)."""
    path = os.path.join(workdir or os.getcwd(), RING_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        pass
    if mgr is not None:
        try:
            from . import manager as manager_mod
            return manager_mod.get_value(mgr, "shm_ring")
        except Exception:
            return None
    return None

_attach_lock = threading.Lock()


def _supports_track_kwarg():
    import inspect
    from multiprocessing import shared_memory
    try:
        return "track" in inspect.signature(
            shared_memory.SharedMemory.__init__).parameters
    except (TypeError, ValueError):
        return False


_HAS_TRACK = _supports_track_kwarg()


def _attach_untracked(name):
    """Open an existing segment WITHOUT resource-tracker registration.

    Python 3.12's SharedMemory registers ATTACHES with the resource
    tracker too, whose exit handler would unlink the segment when a
    short-lived feeder task exits (bpo-38119). Only the creator may own
    the name — unregister-after-attach would instead delete the creator's
    entry in a fork-shared tracker.

    On 3.13+ attaches pass ``track=False`` natively, so concurrent
    SharedMemory creation on other threads is never affected.  On 3.12
    the fallback patches ``resource_tracker.register`` process-wide for
    the duration of the attach; `_attach_lock` serializes our own
    attaches, and the window is a single shm_open — an unrelated create
    racing it would skip tracker registration (leaking that name on
    abnormal exit), which is why the native kwarg is preferred whenever
    present."""
    from multiprocessing import resource_tracker, shared_memory
    if _HAS_TRACK:
        return shared_memory.SharedMemory(name=name, create=False,
                                          track=False)
    with _attach_lock:
        orig = resource_tracker.register
        resource_tracker.register = lambda name, rtype: None
        try:
            return shared_memory.SharedMemory(name=name, create=False)
        finally:
            resource_tracker.register = orig


class ShmChunkRing:
    """Fixed-slot shared-memory ring; see module docstring for protocol."""

    def __init__(self, shm_obj, nslots, slot_bytes, owner):
        self._shm = shm_obj
        self._buf = shm_obj.buf
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        self._owner = owner
        self._unlinked = False

    # -- lifecycle -----------------------------------------------------

    @classmethod
    def create(cls, slot_bytes=None, nslots=None, name=None):
        from multiprocessing import shared_memory

        if slot_bytes is None or nslots is None:
            total_mb = int(os.environ.get("TFOS_TPU_RING_MB", DEFAULT_RING_MB))
            nslots = nslots or DEFAULT_NSLOTS
            slot_bytes = slot_bytes or max((total_mb << 20) // nslots, 1 << 16)
        assert nslots >= 2 and _STATE_OFF + nslots <= _HEADER_BYTES
        name = name or f"tfos_ring_{uuid.uuid4().hex[:12]}"
        size = _HEADER_BYTES + nslots * slot_bytes
        shm_obj = shared_memory.SharedMemory(name=name, create=True, size=size)
        buf = shm_obj.buf
        struct.pack_into("<QIIQ", buf, 0, _MAGIC, nslots, 0, 0)
        struct.pack_into("<Q", buf, 16, 0)                  # produced_seq
        struct.pack_into("<Q", buf, 24, slot_bytes)
        buf[_STATE_OFF:_STATE_OFF + nslots] = bytes(nslots)  # all free
        ring = cls(shm_obj, nslots, slot_bytes, owner=True)
        logger.info("created shm ring %s (%d slots x %d bytes)",
                    name, nslots, slot_bytes)
        return ring

    @classmethod
    def attach(cls, info):
        shm_obj = _attach_untracked(info["name"])
        buf = shm_obj.buf
        magic, nslots, _, _ = struct.unpack_from("<QIIQ", buf, 0)
        if magic != _MAGIC:
            shm_obj.close()
            raise ValueError(f"{info['name']}: not a tfos ring segment")
        (slot_bytes,) = struct.unpack_from("<Q", buf, 24)
        return cls(shm_obj, nslots, slot_bytes, owner=False)

    def info(self):
        return {"name": self._shm.name, "nslots": self.nslots,
                "slot_bytes": self.slot_bytes}

    @property
    def capacity_bytes(self):
        return self.nslots * self.slot_bytes

    def close(self):
        try:
            self._buf = None
            self._shm.close()
        except Exception:
            pass

    def unlink(self):
        """Remove the name (idempotent). Existing mappings stay valid on
        POSIX; only new attaches fail — safe to call at shutdown while a
        consumer is still draining."""
        if self._unlinked:
            return
        self._unlinked = True
        try:
            self._shm.unlink()
        except FileNotFoundError:
            # somebody else (the cluster shutdown closure) removed the name;
            # still drop the creator's tracker entry so its exit handler
            # doesn't warn about a "leaked" segment
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:
                pass
        except Exception:
            logger.debug("ring unlink failed", exc_info=True)

    @staticmethod
    def unlink_by_name(name):
        """Remove the segment name from a process that never created it.
        Unlinks via the raw syscall: attaching a SharedMemory object here
        would re-enter the tracker bookkeeping this module keeps balanced."""
        try:
            import _posixshmem
            _posixshmem.shm_unlink("/" + name.lstrip("/"))
        except FileNotFoundError:
            pass
        except Exception:
            logger.debug("ring unlink(%s) failed", name, exc_info=True)

    # -- low-level slot protocol ---------------------------------------

    def _state(self, seq):
        return self._buf[_STATE_OFF + (seq % self.nslots)]

    def _set_state(self, seq, value):
        self._buf[_STATE_OFF + (seq % self.nslots)] = value

    def _produced_seq(self):
        return struct.unpack_from("<Q", self._buf, 16)[0]

    def _set_produced_seq(self, seq):
        struct.pack_into("<Q", self._buf, 16, seq)

    def _wait_free(self, seq, deadline, should_abort=None):
        delay = 0.0
        next_abort_check = time.time() + 0.25
        while self._state(seq) != _FREE:
            now = time.time()
            if now > deadline:
                raise RingTimeout(
                    f"ring slot {seq % self.nslots} still unconsumed — the "
                    "consumer process is likely dead or stuck")
            if should_abort is not None and now >= next_abort_check:
                should_abort()   # raises to abort the blocked write
                next_abort_check = now + 0.25
            time.sleep(delay)
            delay = min(delay + 0.0002, 0.002)

    # -- producer ------------------------------------------------------

    def write(self, parts, count, timeout=600.0, should_abort=None):
        """Copy ``parts`` (a list of bytes-like objects, written
        back-to-back) into consecutive frames; returns the ShmRef the
        caller must enqueue. Blocks while the ring is full;
        ``should_abort`` (if given) is polled ~4x/s during the wait and
        may raise to abort — e.g. when the consumer reported an error."""
        nbytes = sum(len(p) for p in parts)
        nframes = max(1, -(-nbytes // self.slot_bytes))
        if nframes > self.nslots:
            raise ValueError(
                f"payload of {nbytes} bytes needs {nframes} frames; ring has "
                f"{self.nslots} (raise TFOS_TPU_RING_MB or shrink chunks)")
        seq0 = self._produced_seq()
        deadline = time.time() + timeout
        frame = 0                      # current frame index
        frame_used = 0                 # bytes already written in it
        marked = 0                     # frames this write has set FULL
        try:
            self._wait_free(seq0, deadline, should_abort)
            base = _HEADER_BYTES + (seq0 % self.nslots) * self.slot_bytes
            for part in parts:
                view = memoryview(part).cast("B")
                off = 0
                while off < len(view):
                    if frame_used == self.slot_bytes:
                        self._set_state(seq0 + frame, _FULL)
                        marked += 1
                        frame += 1
                        frame_used = 0
                        self._wait_free(seq0 + frame, deadline, should_abort)
                        base = _HEADER_BYTES + \
                            ((seq0 + frame) % self.nslots) * self.slot_bytes
                    take = min(len(view) - off, self.slot_bytes - frame_used)
                    dst = base + frame_used
                    self._buf[dst:dst + take] = view[off:off + take]
                    frame_used += take
                    off += take
                view.release()
            self._set_state(seq0 + frame, _FULL)
            marked += 1
        except BaseException:
            # A partial write (timeout/abort on a later frame) has marked
            # frames FULL without advancing produced_seq; since no ShmRef
            # was enqueued the consumer will never free them, and the NEXT
            # write would block in _wait_free forever.  Restore the
            # invariant before propagating — but ONLY for frames this
            # write marked: a slot whose _wait_free raised (on a wrapped
            # ring) still holds an older un-consumed payload, and forcing
            # it FREE would let a retrying feeder overwrite live data.
            for k in range(marked):
                try:
                    self._set_state(seq0 + k, _FREE)
                except Exception:
                    break
            raise
        assert frame + 1 == nframes, (frame, nframes, nbytes)
        self._set_produced_seq(seq0 + nframes)
        return ShmRef(seq0, nframes, nbytes, count)

    # -- consumer ------------------------------------------------------

    def read(self, ref):
        """Decode the payload a ref points at, then free its frames.
        Returns what `decode_payload` returns."""
        if ref.nframes == 1:
            base = _HEADER_BYTES + (ref.seq % self.nslots) * self.slot_bytes
            view = self._buf[base:base + ref.nbytes]
            try:
                out = decode_payload(view)
            finally:
                if isinstance(view, memoryview):
                    view.release()
                self._set_state(ref.seq, _FREE)
            return out
        data = bytearray(ref.nbytes)
        off = 0
        for k in range(ref.nframes):
            take = min(self.slot_bytes, ref.nbytes - off)
            base = _HEADER_BYTES + \
                ((ref.seq + k) % self.nslots) * self.slot_bytes
            data[off:off + take] = self._buf[base:base + take]
            self._set_state(ref.seq + k, _FREE)
            off += take
        # copy=False: the bytearray is privately owned and kept alive by
        # the column arrays referencing it — a second per-column copy
        # (needed for ring-backed views, whose slots get reused) would
        # double the memcpy cost of every multi-frame payload
        return decode_payload(memoryview(data), copy=False)

    def skip(self, ref):
        """Free a ref's frames without decoding (terminate()-style drains)."""
        for k in range(ref.nframes):
            self._set_state(ref.seq + k, _FREE)


# -- payload codec -----------------------------------------------------
#
# payload := u32 meta_len | pickle(meta) | buffer bytes...
# meta    := {"k": "p", "rt": tag, "mx": bool,
#             "cols": [(dtype_str, shape), ...]}      packed columnar
#          | {"k": "o"}                               one pickle blob
#          | {"k": "m", "lens": [...]}                concatenated payloads
#
# The "m" (multi) kind coalesces several chunks into ONE ring write + ONE
# queue ref: each queue operation costs a manager-server round trip
# (~1-5 ms), so per-payload overhead — not bandwidth — dominates once
# the bytes ride shared memory.

_ROWTYPE_TAGS = {tuple: "t", list: "l", int: "i", float: "f",
                 bool: "b", None: "n"}
_TAG_ROWTYPES = {v: k for k, v in _ROWTYPE_TAGS.items()}


class MultiPayload(list):
    """decode_payload result for "m": a list of sub-chunk payloads
    (PackedChunks and/or record lists), distinguishable from a plain
    record list."""


def encode_chunk(chunk):
    """(meta+buffers parts list, record_count) for a Chunk/PackedChunk."""
    import numpy as np

    if isinstance(chunk, marker.PackedChunk):
        cols = [np.ascontiguousarray(c) for c in chunk.columns]
        meta = {"k": "p", "rt": _ROWTYPE_TAGS[chunk.row_type],
                "mx": chunk.matrix,
                "cols": [(c.dtype.str, c.shape) for c in cols]}
        head = pickle.dumps(meta, protocol=5)
        parts = [struct.pack("<I", len(head)), head]
        parts.extend(c.data.cast("B") for c in cols)
        return parts, len(chunk)
    items = chunk.items if isinstance(chunk, marker.Chunk) else list(chunk)
    head = pickle.dumps({"k": "o"}, protocol=5)
    blob = pickle.dumps(items, protocol=5)
    return [struct.pack("<I", len(head)), head, blob], len(items)


def encode_multi(chunks):
    """Coalesce several Chunk/PackedChunks into one payload parts list.

    Returns ``(parts, total_count)``; decode yields a `MultiPayload` with
    one entry per input chunk, in order.
    """
    lens, all_parts, total = [], [], 0
    for chunk in chunks:
        parts, n = encode_chunk(chunk)
        lens.append(sum(len(p) for p in parts))
        all_parts.append(parts)
        total += n
    head = pickle.dumps({"k": "m", "lens": lens}, protocol=5)
    out = [struct.pack("<I", len(head)), head]
    for parts in all_parts:
        out.extend(parts)
    return out, total


def decode_payload(view, copy=True):
    """Inverse of encode_chunk over one contiguous payload buffer.

    Returns a `marker.PackedChunk`, a plain list of records, or a
    `MultiPayload` of those.  ``copy=True`` materializes columns out of
    the buffer — required when ``view`` aliases ring slots that will be
    reused; pass ``copy=False`` only for privately-owned buffers.
    """
    import numpy as np

    (meta_len,) = struct.unpack_from("<I", view, 0)
    meta = pickle.loads(view[4:4 + meta_len])
    off = 4 + meta_len
    if meta["k"] == "o":
        return pickle.loads(view[off:])
    if meta["k"] == "m":
        subs = MultiPayload()
        for sub_len in meta["lens"]:
            subs.append(decode_payload(view[off:off + sub_len], copy=copy))
            off += sub_len
        return subs
    cols = []
    for dtype_str, shape in meta["cols"]:
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dt.itemsize
        arr = np.frombuffer(view[off:off + nbytes], dtype=dt,
                            count=n).reshape(shape)
        cols.append(arr.copy() if copy else arr)
        off += nbytes
    return marker.PackedChunk(tuple(cols), _TAG_ROWTYPES[meta["rt"]],
                              meta["mx"])


# -- process-local attach cache ---------------------------------------

_attached = {}
_cache_lock = threading.Lock()
_MAX_ATTACHED = 4


def _segment_gone(name):
    """True when the POSIX shm name has been unlinked (Linux exposes
    segments under /dev/shm). Platforms without /dev/shm (macOS) report
    False for everything so we never evict a live mapping."""
    try:
        if not os.path.isdir("/dev/shm"):
            return False
        return not os.path.exists("/dev/shm/" + name.lstrip("/"))
    except OSError:
        return False


def attach_cached(info):
    """Attach once per (process, ring name); feeder tasks and DataFeeds
    call this on every chunk.

    Long-lived executor processes (SPARK_REUSE_WORKER) see a fresh ring
    per cluster.run(); on every new attach, mappings whose segment has
    since been unlinked are closed and dropped so /dev/shm usage stays
    bounded across runs instead of accumulating one dead ~64MB mapping
    per job.
    """
    ring = _attached.get(info["name"])
    if ring is None:
        with _cache_lock:
            ring = _attached.get(info["name"])
            if ring is None:
                for name in [n for n in _attached if _segment_gone(n)]:
                    _attached.pop(name).close()
                # platform-independent bound (covers hosts with no
                # /dev/shm, where _segment_gone cannot see unlinks):
                # tasks run sequentially per executor, so all but the
                # most recent rings are idle — drop the oldest
                while len(_attached) >= _MAX_ATTACHED:
                    _attached.pop(next(iter(_attached))).close()
                ring = ShmChunkRing.attach(info)
                _attached[info["name"]] = ring
    return ring


def ring_enabled():
    return os.environ.get("TFOS_TPU_SHM_RING", "1") not in ("0", "false", "")
