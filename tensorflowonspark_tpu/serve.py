"""Online inference server over an exported model.

Complements the batch CLI (`tensorflowonspark_tpu.inference`, the
Inference.scala analog) with a long-lived HTTP endpoint — the online half
of the serving story the reference delegated to external TF Serving.
Stdlib-only (http.server), TF-Serving-compatible request shape:

    python -m tensorflowonspark_tpu.serve --export_dir /models/m --port 8501

    POST /v1/models/default:predict   {"instances": [{"x": [...]}, ...]}
        -> {"predictions": [{"y": [...]}, ...]}
    GET  /v1/models/default           -> model/engine metadata + health

Engine selection mirrors the batch CLI: the AOT artifact (native PJRT
runner where available) when the export carries one, else the rebuilt
jitted model.  Requests batch within themselves; the device is guarded by
a lock so concurrent requests serialize instead of interleaving
executions.
"""
import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger(__name__)


def build_argparser():
    p = argparse.ArgumentParser(
        prog="tensorflowonspark_tpu.serve",
        description="online inference HTTP server over an exported model")
    p.add_argument("--export_dir", required=True)
    p.add_argument("--model_name", default="default",
                   help="name served under /v1/models/<name>")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8501)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--batch_wait_ms", type=float, default=0.0,
                   help=">0 enables dynamic micro-batching: concurrent "
                        "requests within this window coalesce into one "
                        "device execution (up to --batch_size rows)")
    p.add_argument("--signature_def_key", default=None)
    p.add_argument("--max_new_tokens_limit", type=int, default=512,
                   help="upper bound a :generate request may ask for")
    p.add_argument("--draft_export_dir", default=None,
                   help="a smaller decoder-LM export used as the "
                        "speculative draft for greedy :generate requests "
                        "(identical outputs, faster when the draft agrees)")
    p.add_argument("--draft_k", type=int, default=4,
                   help="draft tokens proposed per verification pass")
    p.add_argument("--generate_slots", type=int, default=0,
                   help=">0 enables continuous batching for :generate — "
                        "this many decode slots; concurrent requests join "
                        "the in-flight batch at token boundaries "
                        "(mutually exclusive with --draft_export_dir)")
    p.add_argument("--generate_read_chunk", type=int, default=8,
                   help="slot batcher readback granularity: tokens reach "
                        "clients in bursts of this size (larger = higher "
                        "throughput on high-latency runtimes, burstier "
                        "streams; 1 = per-token)")
    p.add_argument("--input_mapping", default=None)
    p.add_argument("--output_mapping", default=None)
    p.add_argument("--engine", choices=["auto", "native", "jax", "builder"],
                   default="auto")
    p.add_argument("--verbose", action="store_true")
    return p


def _instances_to_columns(instances, input_names=None):
    """[{feature: value}, ...] -> ({feature: [values]}, n).

    Also accepts TF Serving's bare row format ([[...], [...]] or scalars)
    when the model has exactly one input: the values map onto that input.
    """
    if not isinstance(instances, list) or not instances:
        raise ValueError('"instances" must be a non-empty list')
    first = instances[0]
    if not isinstance(first, dict):
        if input_names is not None and len(input_names) == 1:
            return {input_names[0]: list(instances)}, len(instances)
        raise ValueError(
            "each instance must be a {feature: value} object (bare rows are "
            "only accepted for single-input models)")
    cols = {k: [] for k in first}
    for i, inst in enumerate(instances):
        if set(inst) != set(cols):
            raise ValueError(f"instance {i} features {sorted(inst)} differ "
                             f"from instance 0 {sorted(cols)}")
        for k, v in inst.items():
            cols[k].append(v)
    return cols, len(instances)


def _rows_from_outputs(outputs, n):
    """{out_col: array-like [n, ...]} -> [{out_col: value}, ...]."""
    import numpy as np

    listed = {name: np.asarray(col).tolist() for name, col in outputs.items()}
    return [{name: listed[name][i] for name in listed} for i in range(n)]


class _MicroBatcher:
    """Coalesce concurrent predict calls into one device execution — the
    TF-Serving request-batching analog (the reference's JVM TFModel got
    the same effect from partition-granular batching,
    TFModel.scala:121-239).  The first request opens a window of
    ``wait_ms``; requests arriving within it are merged (up to
    ``max_batch`` rows) into one columnar execution, and each caller's
    future receives exactly its row slice.  A lone request pays at most
    ``wait_ms`` extra latency; concurrent bursts pay ONE device dispatch
    instead of N serialized ones."""

    def __init__(self, predict_cols, wait_ms=5.0, max_batch=256):
        import queue as queue_mod

        self._predict = predict_cols
        self._wait_s = wait_ms / 1e3
        self._max = max_batch
        self._q = queue_mod.Queue()
        self.executions = 0
        t = threading.Thread(target=self._loop, name="serve-batcher",
                             daemon=True)
        t.start()

    def submit(self, cols, n):
        import concurrent.futures as cf

        fut = cf.Future()
        self._q.put((cols, n, fut))
        return fut.result()

    def _loop(self):
        import queue as queue_mod
        import time as time_mod

        while True:
            batch = [self._q.get()]
            total = batch[0][1]
            deadline = time_mod.monotonic() + self._wait_s
            while total < self._max:
                remaining = deadline - time_mod.monotonic()
                if remaining <= 0:
                    break
                try:
                    item = self._q.get(timeout=remaining)
                except queue_mod.Empty:
                    break
                batch.append(item)
                total += item[1]
            # per-request validation BEFORE merging: a malformed request
            # fails alone instead of poisoning every future coalesced
            # into its window
            head_keys = set(batch[0][0])
            good = []
            for item in batch:
                cols, _, fut = item
                if set(cols) != head_keys:
                    fut.set_exception(ValueError(
                        f"request features {sorted(cols)} differ from "
                        f"batch head {sorted(head_keys)}"))
                else:
                    good.append(item)
            if not good:
                continue
            try:
                merged = {k: [] for k in head_keys}
                for cols, _, _ in good:
                    for k, v in cols.items():
                        merged[k].extend(v)
                total = sum(n for _, n, _ in good)
                outputs = self._predict(merged, total)
                self.executions += 1
                import numpy as np
                arrays = {k: np.asarray(v) for k, v in outputs.items()}
                off = 0
                for _, n, fut in good:
                    fut.set_result(
                        {k: a[off:off + n] for k, a in arrays.items()})
                    off += n
            except Exception as e:
                # result distribution included: ANY escape here would kill
                # the batcher thread and wedge every future submit forever
                for _, _, fut in good:
                    if not fut.done():
                        fut.set_exception(e)


class ModelService:
    """Loads the predictor once; thread-safe predict over JSON instances.

    ``batch_wait_ms > 0`` enables dynamic micro-batching: concurrent
    requests coalesce into one device execution (see _MicroBatcher).
    """

    def __init__(self, args):
        from . import inference

        self._predict_rows, self.desc = inference._load_predictor(args)
        self._lock = threading.Lock()
        self.export_dir = args.export_dir
        self.model_name = getattr(args, "model_name", "default")
        self.requests = 0
        self._gen = None                # lazy GenerateService (or False =
        self._gen_lock = threading.Lock()   # probed and not a decoder LM)
        self._max_new_limit = getattr(args, "max_new_tokens_limit", 512)
        self._draft_dir = getattr(args, "draft_export_dir", None)
        self._draft_k = getattr(args, "draft_k", 4)
        self._gen_slots = getattr(args, "generate_slots", 0) or 0
        self._gen_read_chunk = getattr(args, "generate_read_chunk", 8) or 8
        self._batcher = None
        wait_ms = getattr(args, "batch_wait_ms", 0) or 0
        if wait_ms > 0:
            self._batcher = _MicroBatcher(
                self._predict_rows, wait_ms=wait_ms,
                max_batch=getattr(args, "batch_size", 64) or 64)

    def predict(self, instances):
        cols, n = _instances_to_columns(
            instances, getattr(self._predict_rows, "input_names", None))
        if self._batcher is not None:
            outputs = self._batcher.submit(cols, n)
            with self._lock:
                self.requests += 1
            return _rows_from_outputs(outputs, n)
        with self._lock:   # one device: serialize executions
            outputs = self._predict_rows(cols, n)
            self.requests += 1
        return _rows_from_outputs(outputs, n)

    def generate_service(self):
        """Lazily-built GenerateService, or None when the export's builder
        does not rebuild a decoder LM (probed once)."""
        with self._gen_lock:
            if self._gen is None:
                try:
                    self._gen = GenerateService(
                        self.export_dir,
                        max_new_tokens_limit=self._max_new_limit,
                        draft_export_dir=self._draft_dir,
                        draft_k=self._draft_k, slots=self._gen_slots,
                        read_chunk=self._gen_read_chunk)
                except (TypeError, ValueError) as e:
                    logger.info(":generate unavailable: %s", e)
                    self._gen = False
            return self._gen or None

    def metadata(self):
        out = {"model": {"export_dir": self.export_dir,
                         "engine": self.desc,
                         "requests_served": self.requests},
               "status": "ok"}
        if self._batcher is not None:
            out["model"]["batched_executions"] = self._batcher.executions
        if self._gen is not None:      # only report once probed (lazily)
            out["model"]["generate"] = ("available" if self._gen
                                        else "unavailable")
            if self._gen and self._gen.batcher is not None:
                out["model"]["generate_slots"] = self._gen.batcher.n_slots
        return out


class SlotHandle:
    """One in-flight generation in the continuous batcher: tokens stream
    into `.tokens` as they decode; `.result()` blocks for the full
    sequence."""

    def __init__(self, prompt):
        import queue as queue_mod

        self.prompt = list(prompt)
        self.tokens = queue_mod.Queue()   # ints, then None sentinel
        self.cancelled = threading.Event()
        self._done = threading.Event()
        self._seq = None
        self._err = None

    def cancel(self):
        """Stop decoding for this request (client gone): the batcher
        retires its slot at the next readback boundary."""
        self.cancelled.set()

    def _finish(self, seq):
        self._seq = seq
        self._done.set()
        self.tokens.put(None)

    def _fail(self, err):
        self._err = err
        self._done.set()
        self.tokens.put(None)

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("generation did not complete in time")
        if self._err is not None:
            raise self._err
        return self._seq


class ContinuousBatcher:
    """Slot-based continuous batching over the per-row kv cache
    (models.decode `decode_slots`): new requests PREFILL into a free slot
    at a token boundary while the other slots keep decoding; finished
    slots retire immediately.  The device runs one fused step per token
    for the whole slot batch, so N concurrent streams cost ~one stream's
    step rate (batching is near-free: BASELINE.md round 3 measured B8 at
    ~1.3x the B1 step cost) instead of running back-to-back.

    Greedy decoding is token-identical to `decode.generate`; sampled
    requests draw per-row from a per-step key (a different noise schedule
    than a solo run — documented serving semantics).  Net-new beyond the
    reference (no generation serving there at all).
    """

    def __init__(self, model, params, n_slots=8, max_pending=1024,
                 read_chunk=8, seed=0):
        import queue as queue_mod

        import jax
        import jax.numpy as jnp

        from .models import decode as decode_mod

        self.model, self.params = model, params
        self.slot_model, self._cache = decode_mod.init_slot_cache(model,
                                                                  n_slots)
        self._prefill = decode_mod._jitted_slot_prefill(self.slot_model)
        self._step = decode_mod._jitted_slot_step(self.slot_model)
        self._set_row = decode_mod._jitted_set_row(self.slot_model)
        self.n_slots = n_slots
        self.max_seq = self.slot_model.cfg.max_seq_len
        self.read_chunk = max(1, read_chunk)
        self._pending = queue_mod.Queue(max_pending)
        self._slots = [None] * n_slots
        self._gen = [0] * n_slots      # occupant generation per row: tokens
        # decoded for a previous occupant must never reach a new one
        # device-resident chains: ONE dispatch per decoded token
        self._toks = jnp.zeros((n_slots,), jnp.int32)
        self._temps = jnp.zeros((n_slots,), jnp.float32)
        self._rng = jax.random.key(seed)
        self._steps = 0
        self._dead = None     # set to the fatal exception if the loop dies
        self.requests = 0
        threading.Thread(target=self._loop, name="slot-batcher",
                         daemon=True).start()

    def submit(self, prompt, max_new, temperature=0.0, eos_id=None, seed=0):
        if self._dead is not None:
            raise RuntimeError(f"batcher died: {self._dead}")
        if len(prompt) + max_new > self.max_seq:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new} exceeds "
                f"max_seq_len {self.max_seq}")
        h = SlotHandle(prompt)
        self._pending.put((h, list(prompt), max_new, float(temperature),
                           eos_id, int(seed)))
        if self._dead is not None:
            # the loop may have died between the check above and the put
            # (its death-drain already ran): fail whatever is queued,
            # including our own item, so no handler blocks forever
            self._drain_pending(RuntimeError(f"batcher died: {self._dead}"))
        return h

    def _drain_pending(self, err):
        import queue as queue_mod

        while True:
            try:
                item = self._pending.get_nowait()
            except queue_mod.Empty:
                return
            item[0]._fail(err)

    # ---- device loop (single driver thread owns the cache) --------------

    def _pick_first(self, logits_row, temperature, seed):
        import jax
        import jax.numpy as jnp

        if temperature > 0:
            return int(jax.random.categorical(
                jax.random.fold_in(jax.random.key(seed), 0),
                logits_row / temperature))
        return int(jnp.argmax(logits_row))

    def _do_prefill(self, row, item):
        import jax.numpy as jnp

        h, prompt, max_new, temp, eos_id, seed = item
        if h.cancelled.is_set():        # client gone before admission
            h._finish(list(prompt))
            return
        L = len(prompt)
        bucket = min(max(8, 1 << (L - 1).bit_length()), self.max_seq)
        padded = prompt + [0] * (bucket - L)
        logits, self._cache = self._prefill(
            self.params, self._cache, jnp.asarray([padded], jnp.int32),
            jnp.asarray(row, jnp.int32), jnp.asarray(L, jnp.int32))
        tok = self._pick_first(logits[0], temp, seed)
        h.tokens.put(tok)
        seq = prompt + [tok]
        if max_new <= 1 or (eos_id is not None and tok == eos_id):
            h._finish(seq)
            self.requests += 1
            return
        self._gen[row] += 1
        self._toks, self._temps = self._set_row(
            self._toks, self._temps, jnp.asarray(row, jnp.int32),
            jnp.asarray(tok, jnp.int32), jnp.asarray(temp, jnp.float32))
        self._slots[row] = {"handle": h, "seq": seq,
                            "remaining": max_new - 1, "temp": temp,
                            "eos": eos_id}

    def _admit(self, block=False):
        import queue as queue_mod

        for row in range(self.n_slots):
            if self._slots[row] is not None:
                continue
            try:
                item = self._pending.get(timeout=0.05 if block else 0)
            except queue_mod.Empty:
                return
            self._do_prefill(row, item)
            block = False    # only the first admit may block (idle wake)

    def _process_batch(self, batch):
        """One arrived [k, n_slots] token block -> emissions/retires, in
        dispatch order.  `batch` is (stacked_dev, [gen_snapshot per step])
        whose host copy was started earlier (copy_to_host_async), so the
        np.asarray here is usually free."""
        import numpy as np

        stacked, gens_list = batch
        block = np.asarray(stacked)
        for gens, row_toks in zip(gens_list, block):
            for r, s in enumerate(self._slots):
                if s is None or self._gen[r] != gens[r]:
                    continue      # freed or re-occupied since dispatch
                if s["handle"].cancelled.is_set():
                    # client gone: stop burning device time on this slot
                    s["handle"]._finish(s["seq"])
                    self.requests += 1
                    self._slots[r] = None
                    continue
                tok = int(row_toks[r])
                s["seq"].append(tok)
                s["remaining"] -= 1
                s["handle"].tokens.put(tok)
                if s["remaining"] <= 0 or (s["eos"] is not None
                                           and tok == s["eos"]):
                    s["handle"]._finish(s["seq"])
                    self.requests += 1
                    self._slots[r] = None   # row frees; steps already in
                    # flight for it decode garbage that _gen filters out

    def _loop(self):
        import jax.numpy as jnp

        try:
            reads = []       # dispatched this chunk: [(nxt_dev, gens)]
            inflight = None  # previous chunk, host copy in progress
            while True:
                idle = (all(s is None for s in self._slots)
                        and not reads and inflight is None)
                self._admit(block=idle)
                active = any(s is not None for s in self._slots)
                if active:
                    # ONE dispatch: token/rng/temp chains stay on device
                    nxt, self._cache, self._rng = self._step(
                        self.params, self._cache, self._toks, self._temps,
                        self._rng)
                    self._toks = nxt
                    self._steps += 1
                    reads.append((nxt, tuple(self._gen)))
                # Readback protocol (measured on the tunneled runtime:
                # per-token sync d2h ~200 ms regardless of size): stack a
                # chunk, START its host copy asynchronously, and process
                # the PREVIOUS chunk — whose copy has been riding under
                # this chunk's compute and is now free to read.  Steps
                # may overshoot a retiring slot by up to ~2 chunks; the
                # generation filter drops those tokens and the masked
                # cache write makes out-of-range positions no-ops.
                flush = reads and (
                    len(reads) >= self.read_chunk
                    or not active
                    or min((s["remaining"] for s in self._slots
                            if s is not None), default=0) <= len(reads))
                if flush:
                    stacked = jnp.stack([r[0] for r in reads])
                    gens = [r[1] for r in reads]
                    try:
                        stacked.copy_to_host_async()
                    except Exception:
                        pass             # not all backends support it
                    prev, inflight = inflight, (stacked, gens)
                    reads = []
                    if prev is not None:
                        self._process_batch(prev)
                elif inflight is not None and not active and not reads:
                    # nothing more to dispatch: drain the in-flight chunk
                    self._process_batch(inflight)
                    inflight = None
        except BaseException as e:     # device failure: fail everything
            logger.exception("continuous batcher died")
            self._dead = e
            for s in self._slots:
                if s is not None:
                    s["handle"]._fail(e)
            self._slots = [None] * self.n_slots
            self._drain_pending(e)


class GenerateService:
    """Autoregressive generation over an exported decoder LM.

    Rebuilds the exported module (export.load_model) and serves
    ``models.decode.generate`` — kv-cache greedy/sampled continuation.
    Only exports whose builder rebuilds a ``Transformer`` qualify; the
    endpoint reports 404 otherwise.  Constructed LAZILY on the first
    :generate request so forward-only serving never pays a second param
    load.

    Prompts are grouped by length (static shapes per compiled decode
    step); equal-length prompts in one request batch into one prefill +
    scan.
    """

    @staticmethod
    def _load_lm(export_dir):
        from . import export as export_mod
        from .models.transformer import Transformer

        built, params, _ = export_mod.load_model(export_dir)
        if not isinstance(built, Transformer):
            raise TypeError(
                f"export builder rebuilds {type(built).__name__}, not a "
                "Transformer — :generate serves decoder LMs only")
        import jax
        import jax.numpy as jnp

        compute = jnp.dtype(built.cfg.dtype)
        if jnp.issubdtype(compute, jnp.floating) and compute != jnp.float32:
            # serving reads every weight once per decoded token: store the
            # params at the model's compute width (W16) instead of the f32
            # masters — measured 1.6x decode throughput on the flagship
            # (BASELINE.md round 3)
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        return built, params

    def __init__(self, export_dir, max_new_tokens_limit=512,
                 draft_export_dir=None, draft_k=4, slots=0, read_chunk=8):
        self.model, self.params = self._load_lm(export_dir)
        self.draft_model = self.draft_params = None
        self.draft_k = draft_k
        if slots and draft_export_dir:
            raise ValueError("--generate_slots and --draft_export_dir are "
                             "mutually exclusive (speculation verifies "
                             "whole blocks; slots retire per token)")
        if draft_export_dir:
            # speculative decoding: greedy requests verify k draft tokens
            # per target pass — EXACTLY the same tokens (the draft only
            # changes speed), so no request-level opt-in is needed
            self.draft_model, self.draft_params = \
                self._load_lm(draft_export_dir)
        self.batcher = (ContinuousBatcher(self.model, self.params,
                                          n_slots=slots,
                                          read_chunk=read_chunk)
                        if slots else None)
        self.limit = max_new_tokens_limit
        self._lock = threading.Lock()
        self.requests = 0
        # warm the loop-driver probe at LOAD time (service construction is
        # already the slow path): the first :generate request must not pay
        # two probe compiles while holding self._lock
        import os

        from .models import decode
        if os.environ.get("TFOS_TPU_DECODE_LOOP") is None:
            decode.probe_loop_driver()

    def _validate(self, req):
        import jax

        inputs = req.get("inputs")
        if (not isinstance(inputs, list) or not inputs
                or not all(isinstance(p, list) and p and
                           all(isinstance(t, int) for t in p)
                           for p in inputs)):
            raise ValueError('"inputs" must be a non-empty list of '
                             "non-empty token-id lists")
        max_new = req.get("max_new_tokens", 16)
        if not isinstance(max_new, int) or not 1 <= max_new <= self.limit:
            raise ValueError(f'"max_new_tokens" must be an int in '
                             f"[1, {self.limit}]")
        temperature = float(req.get("temperature", 0.0))
        if temperature < 0:
            raise ValueError('"temperature" must be >= 0')
        eos_id = req.get("eos_id")
        if eos_id is not None and not isinstance(eos_id, int):
            raise ValueError('"eos_id" must be an int')
        rng = (jax.random.key(int(req.get("seed", 0)))
               if temperature > 0 else None)
        return inputs, max_new, temperature, eos_id, rng

    def stream(self, req):
        """Yield JSON-able events for a single-prompt generation:
        ``{"token": t}`` per decoded token (eos-trimmed), then
        ``{"done": true, "output": [...full sequence...]}``."""
        import queue as queue_mod

        import numpy as np

        import jax.numpy as jnp

        from .models import decode

        # validate EAGERLY (before any response bytes): a malformed
        # request must 400, not die mid-stream after a 200 header
        inputs, max_new, temperature, eos_id, rng = self._validate(req)
        if len(inputs) != 1:
            raise ValueError('"stream": true serves exactly one prompt '
                             "per request")
        if self.batcher is not None:
            h = self.batcher.submit(inputs[0], max_new,
                                    temperature=temperature, eos_id=eos_id,
                                    seed=int(req.get("seed", 0)))

            def slot_events():
                try:
                    while True:
                        tok = h.tokens.get()
                        if tok is None:
                            break
                        yield {"token": tok}
                    yield {"done": True, "output": h.result()}
                finally:
                    # consumer died/finished: free the slot instead of
                    # decoding to max_new for a client nobody serves
                    h.cancel()

            return slot_events()
        prompt = jnp.asarray(np.asarray(inputs, np.int32))
        seq = list(inputs[0])
        # Decode runs in its own thread; the handler thread drains this
        # queue and writes the socket OUTSIDE self._lock.  Sized to hold
        # the entire stream (tokens + done + sentinel) so the decode loop
        # can always run to completion and release the lock even when the
        # client stops reading — a stalled socket wedges only its own
        # handler thread, never other :generate requests.
        q = queue_mod.Queue(maxsize=max_new + 2)
        cancelled = threading.Event()

        def produce():
            try:
                with self._lock:
                    for tok_arr in decode.generate_stream(
                            self.model, self.params, prompt, max_new,
                            temperature=temperature, rng=rng, eos_id=eos_id):
                        tok = int(tok_arr[0])
                        seq.append(tok)
                        q.put({"token": tok})
                        if cancelled.is_set():
                            # client gone: stop burning device time; shapes
                            # stay static device-side, the loop just ends
                            q.put(None)
                            return
                        if eos_id is not None and tok == eos_id:
                            break       # stream ends at eos
                    self.requests += 1
                q.put({"done": True, "output": seq})
            except Exception as e:      # surfaced as a stream error event
                q.put(e)
            q.put(None)                 # end-of-stream sentinel

        threading.Thread(target=produce, name="generate-stream",
                         daemon=True).start()

        def events():
            try:
                while True:
                    item = q.get()
                    if item is None:
                        return
                    if isinstance(item, Exception):
                        raise item
                    yield item
            finally:
                cancelled.set()   # consumer died/finished: tell the
                # producer to stop decoding for a client nobody serves

        return events()

    def generate(self, req):
        import numpy as np

        import jax
        import jax.numpy as jnp

        from .models import decode

        inputs, max_new, temperature, eos_id, rng = self._validate(req)
        if self.batcher is not None:
            # continuous batching: every prompt becomes a slot request;
            # they decode concurrently with each other AND with other
            # HTTP requests' prompts (no service lock on this path — the
            # batcher's driver thread owns the device)
            seed = int(req.get("seed", 0))
            handles = [self.batcher.submit(p, max_new,
                                           temperature=temperature,
                                           eos_id=eos_id, seed=seed + i)
                       for i, p in enumerate(inputs)]
            outs = [h.result(timeout=600) for h in handles]
            self.requests += 1
            return outs
        # group by prompt length: each group is one static-shape batch
        groups = {}
        for i, p in enumerate(inputs):
            groups.setdefault(len(p), []).append(i)
        outs = [None] * len(inputs)
        use_draft = (self.draft_model is not None and temperature == 0
                     and eos_id is None)
        with self._lock:
            for g, (length, idxs) in enumerate(sorted(groups.items())):
                prompt = jnp.asarray(
                    np.stack([inputs[i] for i in idxs]), jnp.int32)
                if use_draft and length + max_new + self.draft_k > min(
                        self.model.cfg.max_seq_len,
                        self.draft_model.cfg.max_seq_len):
                    # speculation needs k cache slots of headroom; fall
                    # back to vanilla decode near the length limit
                    use_draft = False
                if use_draft:
                    seq = decode.speculative_generate(
                        self.model, self.params, self.draft_model,
                        self.draft_params, prompt,
                        max_new_tokens=max_new, k=self.draft_k)
                else:
                    # fresh key per length group (otherwise every group in
                    # one request samples identical noise); group 0 keeps
                    # the request key so single-group requests match the
                    # streaming path token-for-token
                    sub = (rng if rng is None or g == 0
                           else jax.random.fold_in(rng, g))
                    seq = decode.generate(self.model, self.params, prompt,
                                          max_new_tokens=max_new,
                                          temperature=temperature, rng=sub,
                                          eos_id=eos_id)
                for row, i in zip(np.asarray(seq), idxs):
                    toks = row.tolist()
                    if eos_id is not None and eos_id in toks[length:]:
                        # static shapes pad with eos; trim host-side
                        end = length + toks[length:].index(eos_id) + 1
                        toks = toks[:end]
                    outs[i] = toks
            self.requests += 1
        return outs


class _Handler(BaseHTTPRequestHandler):
    service = None   # injected by make_server
    # chunked transfer (the streaming :generate path) requires HTTP/1.1;
    # every non-stream response sets Content-Length, so keep-alive is safe
    protocol_version = "HTTP/1.1"

    def _send(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        name = self.service.model_name
        if self.path.rstrip("/").endswith(f"/v1/models/{name}") or \
                self.path in ("/healthz", "/"):
            self._send(200, self.service.metadata())
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        name = self.service.model_name
        is_predict = self.path == f"/v1/models/{name}:predict"
        is_generate = self.path == f"/v1/models/{name}:generate"
        if not (is_predict or is_generate):
            self._send(404, {"error": f"unknown path {self.path} (serving "
                             f"model {name!r})"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(req, dict):
                raise ValueError("request body must be a JSON object")
            if is_generate:
                gen = self.service.generate_service()
                if gen is None:
                    self._send(404, {"error": "this export is not a "
                                     "decoder LM; :generate unavailable"})
                    return
                if req.get("stream"):
                    self._stream_events(gen.stream(req))
                else:
                    self._send(200, {"outputs": gen.generate(req)})
            else:
                preds = self.service.predict(req.get("instances"))
                self._send(200, {"predictions": preds})
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            # malformed client input in any shape -> 400
            self._send(400, {"error": str(e) or type(e).__name__})
        except Exception as e:   # keep the server alive on model errors
            logger.exception("predict failed")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _stream_events(self, events):
        """Write newline-delimited JSON events with chunked framing, one
        chunk per event, so clients see tokens as they decode."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(data):
            self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        try:
            for ev in events:
                chunk(json.dumps(ev).encode() + b"\n")
        except Exception as e:   # mid-stream: emit an error event, end clean
            logger.exception("stream failed")
            try:
                chunk(json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}).encode() + b"\n")
            except OSError:
                pass
        try:
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass

    def log_message(self, fmt, *args):
        logger.debug("http: " + fmt, *args)


def make_server(args):
    """Build (server, service); caller runs serve_forever()."""
    # fail FAST on invalid combinations: GenerateService is constructed
    # lazily on the first :generate request, where a config error would
    # otherwise be swallowed by the is-this-a-decoder-LM probe and turn
    # into a misleading 404
    if getattr(args, "generate_slots", 0) and \
            getattr(args, "draft_export_dir", None):
        raise ValueError("--generate_slots and --draft_export_dir are "
                         "mutually exclusive (speculation verifies whole "
                         "blocks; slots retire per token)")
    service = ModelService(args)
    handler = type("BoundHandler", (_Handler,), {"service": service})
    server = ThreadingHTTPServer((args.host, args.port), handler)
    return server, service


def main(argv=None):
    args = build_argparser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(message)s")
    server, service = make_server(args)
    host, port = server.server_address[:2]
    logger.info("serving %s (%s) on http://%s:%d", args.export_dir,
                service.desc, host, port)
    print(f"serving on http://{host}:{port} ({service.desc})", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()


if __name__ == "__main__":
    main()
